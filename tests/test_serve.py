"""Resident-dataset query server (serve/): registry lifecycle, tier
semantics, cross-request determinism under concurrency, batcher window
extremes, program-cache hit accounting, the HTTP front, and the CLI
``serve`` mode.

The load-bearing contract (ISSUE 7 acceptance): batched/coalesced
answers are BIT-IDENTICAL to individual ``api.kselect``/``quantiles``
calls for every tier, dataset residency (incl. the 64-bit-no-x64
host-exact route), coalescing window, and concurrency level; sketch-tier
responses always carry their exact bounds; server start/stop leaks no
threads (the conftest autouse fixture enforces that after every test
here); repeat query shapes hit the registry's program cache.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

import jax

from mpi_k_selection_tpu import api
from mpi_k_selection_tpu import obs as obs_lib
from mpi_k_selection_tpu.serve import (
    DatasetExistsError,
    DatasetNotFoundError,
    KSelectHTTPServer,
    KSelectServer,
    ProgramCache,
    QueryError,
    ServerClosedError,
    start_http_server,
)

# > 2^14 so single exact rank queries take the shared radix walk (the
# same dispatch api.kselect resolves to at this n)
N_BIG = 40_000


@pytest.fixture
def x_int32(rng):
    return rng.integers(-(2**31), 2**31 - 1, size=N_BIG, dtype=np.int32)


def _bits(values, dtype):
    """Bit pattern of ``values`` in ``dtype`` — the comparison every
    bit-identity assertion here uses (float payload-safe)."""
    return np.asarray(values, dtype=dtype).tobytes()


def _serial_reference(x, ks):
    """One api.kselect call per rank — the serial oracle the batched
    server answers must match bit for bit."""
    return [np.asarray(api.kselect(x, int(k))).item() for k in ks]


# ---------------------------------------------------------------------------
# registry lifecycle + program cache


def test_registry_lifecycle(x_int32):
    with KSelectServer() as srv:
        srv.add_dataset("a", x_int32)
        with pytest.raises(DatasetExistsError):
            srv.add_dataset("a", x_int32)
        with pytest.raises(DatasetNotFoundError):
            srv.kselect("missing", 1)
        with pytest.raises(QueryError):
            srv.add_dataset("empty", np.empty(0, np.int32))
        with pytest.raises(QueryError):
            srv.add_dataset("both", x_int32, source=[x_int32])
        rows = srv.list_datasets()
        assert [r["dataset"] for r in rows] == ["a"]
        assert rows[0]["n"] == N_BIG
        assert rows[0]["residency"] == "device"
        assert rows[0]["sketch"] is True
        assert rows[0]["sketch_resolution_bits"] == 16
        srv.drop_dataset("a")
        with pytest.raises(DatasetNotFoundError):
            srv.drop_dataset("a")
        assert srv.list_datasets() == []


def test_rank_and_tier_validation(x_int32):
    with KSelectServer() as srv:
        srv.add_dataset("a", x_int32)
        with pytest.raises(QueryError):
            srv.kselect("a", 0)
        with pytest.raises(QueryError):
            srv.kselect("a", N_BIG + 1)
        with pytest.raises(QueryError):
            srv.kselect("a", 1, tier="warp")
        with pytest.raises(QueryError):
            srv.quantiles("a", [1.5])
        srv.add_dataset("nosketch", x_int32, sketch=False)
        with pytest.raises(QueryError):
            srv.kselect("nosketch", 1, tier="sketch")
        # auto without a sketch never pins: it must fall through to exact
        a = srv.kselect("nosketch", 7, tier="auto")
        assert a.tier == "exact" and a.exact


def test_program_cache_hit_miss_counters(x_int32):
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=obs) as srv:
        srv.add_dataset("a", x_int32)
        assert srv.registry.programs.misses == 0
        srv.kselect("a", 100, tier="exact")
        miss0, hit0 = srv.registry.programs.misses, srv.registry.programs.hits
        assert (miss0, hit0) == (1, 0)
        # the SAME query shape (width-1 rank batch) must hit, not rebuild
        srv.kselect("a", 31_337, tier="exact")
        srv.kselect("a", 7, tier="exact")
        assert srv.registry.programs.misses == miss0
        assert srv.registry.programs.hits == hit0 + 2
        # the walk closure is width-independent (keyed per dataset, so
        # varying coalesced widths can't fragment the LRU): width-2
        # batches HIT the same entry
        srv.kselect_many("a", [5, 6], tier="exact")
        srv.kselect_many("a", [9, 12], tier="exact")
        assert srv.registry.programs.misses == miss0
        assert srv.registry.programs.hits == hit0 + 4
        # the sort path caches the dataset's sorted descent state once
        wide = list(range(1, api.many_sort_dispatch_queries(N_BIG) + 2))
        srv.kselect_many("a", wide, tier="exact")
        srv.kselect_many("a", wide, tier="exact")
        assert srv.registry.programs.misses == miss0 + 1
        # the exported mirror equals the source counters EXACTLY
        snap = srv.collect_metrics().as_dict()
        assert snap["serve.program_cache.hits"]["value"] == srv.registry.programs.hits
        assert (
            snap["serve.program_cache.misses"]["value"]
            == srv.registry.programs.misses
        )
        # dropping the dataset evicts its cached programs
        srv.drop_dataset("a")
        assert len(srv.registry.programs) == 0


def test_program_cache_lru_eviction():
    cache = ProgramCache(max_entries=2)
    assert cache.get_or_build(("a", "d1"), lambda: 1) == 1
    assert cache.get_or_build(("b", "d1"), lambda: 2) == 2
    assert cache.get_or_build(("a", "d1"), lambda: 99) == 1  # hit keeps 1
    cache.get_or_build(("c", "d1"), lambda: 3)  # evicts ("b", ...) (LRU)
    assert cache.get_or_build(("b", "d1"), lambda: 4) == 4  # rebuilt
    assert cache.hits == 1 and cache.misses == 4


# ---------------------------------------------------------------------------
# tier semantics


def test_sketch_tier_always_carries_exact_bounds(x_int32):
    with KSelectServer() as srv:
        srv.add_dataset("a", x_int32)
        s = np.sort(x_int32, kind="stable")
        for k in (1, 17, N_BIG // 2, N_BIG):
            a = srv.kselect("a", k, tier="sketch")
            assert a.tier == "sketch"
            assert a.rank_bounds is not None
            assert a.value_bounds is not None
            assert a.rank_error_bound == a.rank_bounds[1] - a.rank_bounds[0]
            lo, hi = a.rank_bounds
            assert lo < k <= hi  # exact rank bracket, any stream
            v_lo, v_hi = a.value_bounds
            assert v_lo <= s[k - 1] <= v_hi  # exact value bracket
            d = a.as_dict()
            assert {"rank_bounds", "value_bounds", "rank_error_bound"} <= set(d)


def test_auto_tier_pins_and_escalates(x_int32):
    obs = obs_lib.Observability(
        events=obs_lib.ListSink(), metrics=obs_lib.MetricsRegistry()
    )
    with KSelectServer(obs=obs) as srv:
        # constant data: every resolved interval clamps to one key -> auto
        # answers from the sketch, exactly, with zero escalations
        srv.add_dataset("flat", np.full(5000, 42, np.int32))
        for k in (1, 2500, 5000):
            a = srv.kselect("flat", k, tier="auto")
            assert (a.tier, a.exact, a.escalated) == ("sketch", True, False)
            assert a.value == 42
        assert obs.metrics.counter("serve.tier_escalations").value == 0
        # int16 at 4x4 resolves ALL 16 key bits: every rank pins, and the
        # pinned sketch answers are bit-identical to the exact tier
        x16 = np.random.default_rng(7).integers(
            -(2**15), 2**15, size=4096, dtype=np.int16
        )
        srv.add_dataset("i16", x16)
        s16 = np.sort(x16, kind="stable")
        for k in (1, 9, 2048, 4096):
            a = srv.kselect("i16", k, tier="auto")
            assert (a.tier, a.exact) == ("sketch", True)
            assert _bits(a.value, np.int16) == _bits(s16[k - 1], np.int16)
        # spread int32: unpinned -> auto escalates to exact, bit-identical
        # to the direct api call, and the escalation counter says so
        srv.add_dataset("spread", x_int32)
        a = srv.kselect("spread", 1234, tier="auto")
        assert (a.tier, a.exact, a.escalated) == ("exact", True, True)
        assert _bits(a.value, np.int32) == _bits(
            _serial_reference(x_int32, [1234]), np.int32
        )
        assert obs.metrics.counter("serve.tier_escalations").value == 1
        kinds = {e.kind for e in obs.events.events}
        assert {"serve.query", "serve.batch"} <= kinds


def test_sketch_pin_contract(x_int32):
    """RadixSketch.pin: None exactly when the clamped interval holds more
    than one key; the pinned value is the true order statistic."""
    from mpi_k_selection_tpu.streaming.sketch import RadixSketch

    sk = RadixSketch(np.int32).update(x_int32)
    assert sk.pin(N_BIG // 2) is None  # spread data, 16 of 32 bits resolved
    flat = RadixSketch(np.int32).update(np.full(100, -7, np.int32))
    pinned = flat.pin(50)
    assert pinned is not None and pinned == -7


# ---------------------------------------------------------------------------
# cross-request determinism (the acceptance grid)


@pytest.mark.parametrize("window", [0.0, 0.25])
@pytest.mark.parametrize("tier", ["sketch", "exact", "auto"])
def test_concurrent_queries_bit_identical_to_serial(x_int32, tier, window):
    """N threads issuing overlapping kselect/quantile queries produce
    answers bit-identical to serial execution, across every tier and
    with the batcher window at both extremes (0 = no coalescing, large
    = full coalescing)."""
    n_threads = 8
    ks_per_thread = [
        [1 + (i * 977 + j * 131) % N_BIG for j in range(3)]
        for i in range(n_threads)
    ]
    qs = [0.25, 0.9]
    with KSelectServer(window=window) as srv:
        srv.add_dataset("a", x_int32)
        # serial references, one query at a time, BEFORE any concurrency
        serial_ranks = {
            k: srv.kselect("a", k, tier=tier).value
            for row in ks_per_thread
            for k in row
        }
        serial_q = [a.value for a in srv.quantiles("a", qs, tier=tier)]
        if tier != "sketch":  # exact/auto answers match the direct api
            for k, v in serial_ranks.items():
                assert _bits(v, np.int32) == _bits(
                    _serial_reference(x_int32, [k]), np.int32
                )
        results = [None] * n_threads
        errors = []
        barrier = threading.Barrier(n_threads)

        def client(i):
            try:
                barrier.wait(timeout=30)
                out = {}
                for k in ks_per_thread[i]:
                    out[k] = srv.kselect("a", k, tier=tier).value
                out["q"] = [a.value for a in srv.quantiles("a", qs, tier=tier)]
                results[i] = out
            except BaseException as e:  # surfaced below, not swallowed
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,), name=f"client-{i}")
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        for i in range(n_threads):
            assert results[i] is not None
            for k in ks_per_thread[i]:
                assert _bits(results[i][k], np.int32) == _bits(
                    serial_ranks[k], np.int32
                ), (tier, window, k)
            assert _bits(results[i]["q"], np.int32) == _bits(serial_q, np.int32)


def test_batcher_window_extremes(x_int32):
    n_threads = 8
    ks = [1 + 613 * i for i in range(n_threads)]
    want = _serial_reference(x_int32, ks)
    # window=0: every request dispatches alone — batch width is always 1
    obs0 = obs_lib.Observability(
        events=obs_lib.ListSink(), metrics=obs_lib.MetricsRegistry()
    )
    with KSelectServer(window=0.0, obs=obs0) as srv:
        srv.add_dataset("a", x_int32)
        for i, k in enumerate(ks):
            a = srv.kselect("a", k, tier="exact")
            assert _bits(a.value, np.int32) == _bits(want[i], np.int32)
        widths = [e.width for e in obs0.events.of_kind("serve.batch")]
        assert widths and max(widths) == 1
        assert obs0.metrics.histogram("serve.batch_width").max == 1
    # large window: concurrent arrivals coalesce into one shared walk
    obs1 = obs_lib.Observability(
        events=obs_lib.ListSink(), metrics=obs_lib.MetricsRegistry()
    )
    with KSelectServer(window=0.5, obs=obs1) as srv:
        srv.add_dataset("a", x_int32)
        results = [None] * n_threads
        barrier = threading.Barrier(n_threads)

        def client(i):
            barrier.wait(timeout=30)
            results[i] = srv.kselect("a", ks[i], tier="exact").value

        threads = [threading.Thread(target=client, args=(i,)) for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for i in range(n_threads):
            assert _bits(results[i], np.int32) == _bits(want[i], np.int32)
        batches = obs1.events.of_kind("serve.batch")
        assert max(e.width for e in batches) >= 2  # coalescing happened
        assert sum(e.width for e in batches) == n_threads  # nothing lost
        assert max(e.requests for e in batches) >= 2


def test_batch_flips_to_sort_path_bit_identically(x_int32):
    """A coalesced batch past many_sort_dispatch_queries flips to the
    one-sort-K-gathers path (through the cached sort) — answers must
    stay bit-identical to one-at-a-time kselect."""
    sort_at = api.many_sort_dispatch_queries(N_BIG)
    ks = [1 + (i * 409) % N_BIG for i in range(sort_at + 5)]
    with KSelectServer() as srv:
        srv.add_dataset("a", x_int32)
        answers = srv.kselect_many("a", ks, tier="exact")
        assert ("sorted", "a") in srv.registry.programs._entries
        want = np.sort(x_int32, kind="stable")[np.asarray(ks) - 1]
        assert _bits([a.value for a in answers], np.int32) == _bits(
            want, np.int32
        )
        # spot-check against the serial api oracle too
        assert _bits(answers[0].value, np.int32) == _bits(
            _serial_reference(x_int32, [ks[0]]), np.int32
        )


# ---------------------------------------------------------------------------
# residency routes


def test_int64_without_x64_takes_host_exact_stream_route(rng):
    """Caller-typed 64-bit host data with x64 off must not truncate: the
    registry routes it through the streaming layer's host-exact
    counting (KSL002's bug class, closed at the serving layer)."""
    assert not jax.config.jax_enable_x64
    x = rng.integers(-(2**62), 2**62, size=3000, dtype=np.int64)
    s = np.sort(x, kind="stable")
    with KSelectServer() as srv:
        srv.add_dataset("wide", x)
        assert srv.registry.get("wide").residency == "stream"
        for k in (1, 1500, 3000):
            a = srv.kselect("wide", k, tier="exact")
            assert _bits(a.value, np.int64) == _bits(s[k - 1], np.int64)
        # sketch/auto tiers ride the same resident sketch
        b = srv.kselect("wide", 1500, tier="sketch")
        assert b.value_bounds[0] <= s[1499] <= b.value_bounds[1]
        u = rng.integers(0, 2**63, size=1000, dtype=np.uint64)
        srv.add_dataset("u64", u)
        assert srv.registry.get("u64").residency == "stream"
        a = srv.kselect("u64", 500, tier="exact")
        assert _bits(a.value, np.uint64) == _bits(
            np.sort(u, kind="stable")[499], np.uint64
        )


def test_stream_dataset_from_chunked_source(rng):
    chunks = [
        rng.integers(-(2**31), 2**31 - 1, size=1 << 12, dtype=np.int32)
        for _ in range(5)
    ]
    x = np.concatenate(chunks)
    s = np.sort(x, kind="stable")
    with KSelectServer(window=0.2) as srv:
        srv.add_dataset("st", source=chunks, pipeline_depth=0)
        ds = srv.registry.get("st")
        assert ds.residency == "stream" and ds.n == x.size
        qs = [0.1, 0.5, 0.99]
        want = [a for a in np.asarray(api.quantiles(x, qs))]
        got = srv.quantiles("st", qs, tier="exact")
        assert _bits([a.value for a in got], np.int32) == _bits(want, np.int32)
        # repeat shape hits the cached stream-select program
        hits0 = srv.registry.programs.hits
        srv.quantiles("st", qs, tier="exact")
        assert srv.registry.programs.hits == hits0 + 1
        # concurrent clients against the stream dataset stay bit-identical
        results = [None] * 4
        barrier = threading.Barrier(4)

        def client(i):
            barrier.wait(timeout=30)
            results[i] = [a.value for a in srv.quantiles("st", qs, tier="exact")]

        ts = [threading.Thread(target=client, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        for r in results:
            assert _bits(r, np.int32) == _bits(want, np.int32)
        # top-k needs a resident array; streams refuse loudly
        with pytest.raises(QueryError):
            srv.topk("st", 4)
        # ... but the streamed rank certificate works
        less, leq = srv.rank_certificate("st", s[100 - 1])
        assert less < 100 <= leq


def test_float32_and_float64_datasets(rng):
    xf = rng.standard_normal(N_BIG).astype(np.float32)
    with KSelectServer() as srv:
        srv.add_dataset("f32", xf)
        want = _serial_reference(xf, [77, N_BIG // 2])
        got = srv.kselect_many("f32", [77, N_BIG // 2], tier="exact")
        assert _bits([a.value for a in got], np.float32) == _bits(
            want, np.float32
        )
        qa = srv.quantiles("f32", [0.5], tier="auto")[0]
        assert _bits(qa.value, np.float32) == _bits(
            np.asarray(api.quantiles(xf, [0.5])), np.float32
        )
        # float64 on CPU follows as_selection_array's documented
        # conversion; the registered residency serves exactly w.r.t. the
        # resident bits (sketch and exact describe the SAME array)
        xd = rng.standard_normal(2000)
        srv.add_dataset("f64", xd)
        ds = srv.registry.get("f64")
        resident = np.asarray(ds.data)
        a = srv.kselect("f64", 1000, tier="exact")
        assert _bits(a.value, resident.dtype) == _bits(
            np.sort(resident, kind="stable")[999], resident.dtype
        )


def test_topk_and_certificate_match_direct_ops(x_int32):
    from mpi_k_selection_tpu.ops.topk import topk as ops_topk

    with KSelectServer() as srv:
        srv.add_dataset("a", x_int32)
        v, i = srv.topk("a", 8)
        wv, wi = ops_topk(np.asarray(x_int32), 8)
        assert np.array_equal(v, np.asarray(wv))
        assert np.array_equal(i, np.asarray(wi))
        v, i = srv.topk("a", 5, largest=False)
        order = np.argsort(x_int32, kind="stable")[:5]
        assert np.array_equal(i, order)
        ref = _serial_reference(x_int32, [123])[0]
        less, leq = srv.rank_certificate("a", ref)
        assert less < 123 <= leq


# ---------------------------------------------------------------------------
# obs integration


def test_serve_query_events_and_metrics(x_int32):
    obs = obs_lib.Observability.collecting()
    with KSelectServer(obs=obs) as srv:
        srv.add_dataset("a", x_int32)
        srv.kselect("a", 5, tier="exact")
        srv.kselect("a", 5, tier="sketch")
        srv.quantiles("a", [0.5, 0.9], tier="auto")
        srv.topk("a", 3)
        srv.rank_certificate("a", 0)
        events = obs.events.of_kind("serve.query")
        assert [e.op for e in events] == [
            "kselect", "kselect", "quantiles", "topk", "rank_certificate",
        ]
        by_op = {e.op: e for e in events}
        assert by_op["quantiles"].queries == 2
        assert events[1].tier_answered == "sketch"
        snap = srv.collect_metrics().as_dict()
        assert snap['serve.queries{op="kselect",tier="exact"}']["value"] == 1
        assert snap['serve.queries{op="kselect",tier="sketch"}']["value"] == 1
        lat = snap['serve.latency_seconds{tier="exact"}']
        assert lat["count"] >= 3  # exact kselect + quantiles + topk + cert
        assert snap["serve.datasets"]["value"] == 1
        # prometheus exposition renders the namespace
        text = srv.render_prometheus()
        assert "ksel_serve_queries" in text
        assert "ksel_serve_latency_seconds_bucket" in text
        assert "ksel_serve_program_cache_hits" in text


def test_kselect_many_emits_resident_select_event(x_int32):
    sink = obs_lib.ListSink()
    obs = obs_lib.Observability(events=sink)
    api.kselect_many(x_int32, [1, 2, 3], obs=obs)
    small = np.arange(100, dtype=np.int32)
    api.kselect_many(small, [1, 2], obs=obs)
    evs = sink.of_kind("resident.select")
    assert [e.algorithm for e in evs] == ["radix-many", "sort-many"]
    assert [e.queries for e in evs] == [3, 2]


def test_obs_never_changes_answers(x_int32):
    ks = [3, 777, N_BIG]
    with KSelectServer() as srv:
        srv.add_dataset("a", x_int32)
        plain = [a.value for a in srv.kselect_many("a", ks, tier="exact")]
    obs = obs_lib.Observability.collecting()
    with KSelectServer(obs=obs, window=0.05) as srv:
        srv.add_dataset("a", x_int32)
        wired = [a.value for a in srv.kselect_many("a", ks, tier="exact")]
    assert _bits(plain, np.int32) == _bits(wired, np.int32)


# ---------------------------------------------------------------------------
# lifecycle / shutdown


def test_close_is_idempotent_and_rejects_queries(x_int32):
    srv = KSelectServer()
    srv.add_dataset("a", x_int32)
    assert srv.kselect("a", 1, tier="exact").value == int(np.min(x_int32))
    srv.close()
    srv.close()
    with pytest.raises(ServerClosedError):
        srv.kselect("a", 1, tier="exact")
    with pytest.raises(ServerClosedError):
        srv.kselect("a", 1, tier="sketch")


def test_dispatch_errors_surface_on_request_thread(x_int32):
    with KSelectServer() as srv:
        srv.add_dataset("a", x_int32)
        # registry raises INSIDE the dispatch thread for stream-only ops;
        # the error must re-raise on the caller, not kill the dispatcher
        with pytest.raises(QueryError):
            srv.topk("a", 0)
        # the dispatch thread survived: later queries still answer
        assert srv.kselect("a", 1, tier="exact").value == int(np.min(x_int32))


# ---------------------------------------------------------------------------
# HTTP front


def _http(port, method, path, body=None):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        c.request(
            method,
            path,
            None if body is None else json.dumps(body),
            {"Content-Type": "application/json"},
        )
        r = c.getresponse()
        return r.status, r.read()
    finally:
        c.close()


def test_http_front_roundtrip(x_int32):
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(window=0.01, obs=obs) as srv:
        srv.add_dataset("a", x_int32)
        with start_http_server(srv) as h:
            status, body = _http(h.port, "GET", "/healthz")
            assert status == 200 and json.loads(body)["datasets"] == 1
            status, body = _http(h.port, "GET", "/v1/datasets")
            assert status == 200
            assert json.loads(body)["datasets"][0]["dataset"] == "a"
            # exact kselect over the wire == the direct api answer
            want = _serial_reference(x_int32, [1234])[0]
            status, body = _http(
                h.port, "POST", "/v1/query",
                {"dataset": "a", "op": "kselect", "k": 1234, "tier": "exact"},
            )
            assert status == 200
            ans = json.loads(body)["answers"][0]
            assert ans["value"] == int(want)
            assert ans["tier"] == "exact" and ans["exact"] is True
            # sketch tier always ships its bounds
            status, body = _http(
                h.port, "POST", "/v1/query",
                {"dataset": "a", "op": "quantiles", "qs": [0.5], "tier": "sketch"},
            )
            assert status == 200
            ans = json.loads(body)["answers"][0]
            assert {"rank_bounds", "value_bounds", "rank_error_bound"} <= set(ans)
            # topk + certificate ops
            status, body = _http(
                h.port, "POST", "/v1/query",
                {"dataset": "a", "op": "topk", "k": 3},
            )
            assert status == 200
            assert json.loads(body)["values"] == [
                int(v) for v in np.sort(x_int32)[::-1][:3]
            ]
            status, body = _http(
                h.port, "POST", "/v1/query",
                {"dataset": "a", "op": "rank_certificate", "value": int(want)},
            )
            assert status == 200
            cert = json.loads(body)
            assert cert["less"] < 1234 <= cert["leq"]
            # error mapping: 404 unknown dataset, 400 malformed
            status, _ = _http(
                h.port, "POST", "/v1/query",
                {"dataset": "ghost", "op": "kselect", "k": 1},
            )
            assert status == 404
            for bad in (
                {"dataset": "a", "op": "warp"},
                {"dataset": "a", "op": "kselect"},
                {"dataset": "a", "op": "kselect", "k": 0},
                {"op": "kselect", "k": 1},
            ):
                status, _ = _http(h.port, "POST", "/v1/query", bad)
                assert status == 400, bad
            status, _ = _http(h.port, "GET", "/nope")
            assert status == 404
            # /metrics: live Prometheus text of the server namespace,
            # shipped under the exposition content type (ISSUE 14)
            c = http.client.HTTPConnection("127.0.0.1", h.port, timeout=30)
            try:
                c.request("GET", "/metrics")
                r = c.getresponse()
                assert r.status == 200
                assert (
                    r.getheader("Content-Type")
                    == "text/plain; version=0.0.4; charset=utf-8"
                )
                text = r.read().decode()
            finally:
                c.close()
            assert "ksel_serve_queries" in text
            assert "ksel_serve_latency_seconds_bucket" in text
            # the runtime ledger rides every scrape (obs/ledger.py)
            assert "ksel_ledger_compiles" in text
    # context exits joined the HTTP serve loop, request threads, and the
    # dispatch thread — the conftest fixture verifies nothing leaked


def test_http_concurrent_clients_bit_identical(x_int32):
    ks = [1 + 313 * i for i in range(8)]
    want = _serial_reference(x_int32, ks)
    with KSelectServer(window=0.2) as srv:
        srv.add_dataset("a", x_int32)
        with start_http_server(srv) as h:
            results = [None] * len(ks)
            barrier = threading.Barrier(len(ks))

            def client(i):
                barrier.wait(timeout=30)
                status, body = _http(
                    h.port, "POST", "/v1/query",
                    {"dataset": "a", "op": "kselect", "k": ks[i], "tier": "exact"},
                )
                assert status == 200
                results[i] = json.loads(body)["answers"][0]["value"]

            ts = [threading.Thread(target=client, args=(i,)) for i in range(len(ks))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert results == [int(v) for v in want]


# ---------------------------------------------------------------------------
# CLI serve mode


def test_cli_serve_mode(tmp_path):
    from mpi_k_selection_tpu.cli import main

    port_file = tmp_path / "port"
    rc = []
    t = threading.Thread(
        target=lambda: rc.append(
            main(
                [
                    "serve",
                    "--n", "4096",
                    "--dtype", "int32",
                    "--port", "0",
                    "--port-file", str(port_file),
                    "--batch-window", "0",
                    "--quit-after", "2",
                ]
            )
        ),
        name="cli-serve",
    )
    t.start()
    for _ in range(400):  # wait for the listener to come up
        if port_file.exists() and port_file.read_text():
            break
        time.sleep(0.05)
    else:
        pytest.fail("serve CLI never wrote its port file")
    port = int(port_file.read_text())
    status, body = _http(port, "GET", "/healthz")
    assert status == 200
    status, body = _http(
        port, "POST", "/v1/query",
        {"dataset": "default", "op": "kselect", "k": 1, "tier": "exact"},
    )
    assert status == 200
    from mpi_k_selection_tpu.utils import datagen

    x = datagen.generate(4096, pattern="uniform", seed=0, dtype="int32")
    assert json.loads(body)["answers"][0]["value"] == int(np.min(x))
    t.join(timeout=60)
    assert not t.is_alive() and rc == [0]


def test_cli_serve_parser_errors(capsys):
    from mpi_k_selection_tpu.cli import build_serve_parser

    p = build_serve_parser()
    args = p.parse_args([])
    assert args.port == 8080 and args.batch_window == 0.002
    with pytest.raises(SystemExit):
        p.parse_args(["--gen", "nonsense"])
    capsys.readouterr()
