"""kselect-lint: per-rule fixtures (positive + negative + noqa), contract
self-tests, CLI exit codes, and the tier-1 analyzer gate over the whole
repository.

The gate test at the bottom is the PR-blocking one: it runs every AST
rule and every jaxpr contract check over the shipped tree and fails on
any unsuppressed finding, writing the JSON report to
/tmp/kselect_lint.json for debugging.
"""

import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

from mpi_k_selection_tpu.analysis import run_analysis, shared_modules
from mpi_k_selection_tpu.analysis.core import load_module
from mpi_k_selection_tpu.analysis.__main__ import main as lint_main

REPO = pathlib.Path(__file__).resolve().parent.parent


def _lint_source(tmp_path, source, name="mod.py", **kwargs):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    kwargs.setdefault("contracts", False)
    return run_analysis([f], **kwargs)


def _rules_hit(report):
    return {f.rule for f in report.unsuppressed}


# ---------------------------------------------------------------------------
# KSL001 — host sync reachable from jit/shard_map


KSL001_POSITIVE = """
    import jax

    @jax.jit
    def hot(x):
        return int(x) + x.item()

    def helper(x):
        return jax.device_get(x)

    def also_hot(x):
        return jax.jit(inner)(x)

    def inner(x):
        return helper(x)
"""

KSL001_NEGATIVE = """
    import jax
    import numpy as np

    @jax.jit
    def hot(x):
        rows = int(x.shape[0])          # shape-derived: static under trace
        c = np.array(~np.uint64(0))     # constant expression: trace-safe
        return x[:rows] ^ c

    def eager_shell(x):
        return int(jax.jit(lambda v: v + 1)(x))  # sync OUTSIDE the jit fn
"""


def test_ksl001_positive(tmp_path):
    report = _lint_source(tmp_path, KSL001_POSITIVE)
    hits = [f for f in report.unsuppressed if f.rule == "KSL001"]
    # int(x), x.item() in the decorated root; device_get via the
    # jit-wrapped inner -> helper chain
    assert len(hits) >= 3
    assert any("device_get" in f.message for f in hits)


def test_ksl001_negative(tmp_path):
    assert "KSL001" not in _rules_hit(_lint_source(tmp_path, KSL001_NEGATIVE))


def test_ksl001_noqa(tmp_path):
    src = """
    import jax

    @jax.jit
    def hot(x):
        return int(x)  # ksel: noqa[KSL001] -- fixture justification
    """
    report = _lint_source(tmp_path, src)
    assert "KSL001" not in _rules_hit(report)
    sup = [f for f in report.findings if f.rule == "KSL001" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL002 — unguarded 64-bit jnp.asarray


KSL002_POSITIVE = """
    import jax.numpy as jnp
    import numpy as np

    def convert(x):
        if x.dtype == np.int64:
            pass
        return jnp.asarray(x)
"""

KSL002_NEGATIVE = """
    import jax.numpy as jnp
    import numpy as np
    from mpi_k_selection_tpu.utils.dtypes import _require_x64

    def guarded(x):
        if x.dtype == np.int64:
            _require_x64(x.dtype)
        return jnp.asarray(x)

    def explicit(x):
        # an explicit dtype declares the width: not the silent class
        return jnp.asarray(x, jnp.int64)

    def narrow(x):
        return jnp.asarray(x)   # no 64-bit data handled here
"""


def test_ksl002_positive(tmp_path):
    report = _lint_source(tmp_path, KSL002_POSITIVE)
    assert "KSL002" in _rules_hit(report)


def test_ksl002_negative(tmp_path):
    assert "KSL002" not in _rules_hit(_lint_source(tmp_path, KSL002_NEGATIVE))


def test_ksl002_noqa(tmp_path):
    src = KSL002_POSITIVE.replace(
        "return jnp.asarray(x)",
        "return jnp.asarray(x)  # ksel: noqa[KSL002] -- guarded upstream",
    )
    assert "KSL002" not in _rules_hit(_lint_source(tmp_path, src))


# ---------------------------------------------------------------------------
# KSL003 — _Descent outside the f64 warning shells


KSL003_POSITIVE = """
    from mpi_k_selection_tpu.ops.radix import _Descent

    def my_select(x):
        prep = _Descent(x, None, "auto", 32768)
        return prep
"""

KSL003_NEGATIVE = """
    from mpi_k_selection_tpu.ops.radix import (
        _Descent, _f64_exact_shell, _warn_f64_tpu_approx,
    )

    def warned_select(x):
        _warn_f64_tpu_approx(x)
        return _Descent(x, None, "auto", 32768)

    def traced(x):
        return _Descent(x, None, "auto", 32768)

    def shell(x):
        return _f64_exact_shell(traced, x)
"""


def test_ksl003_positive(tmp_path):
    assert "KSL003" in _rules_hit(_lint_source(tmp_path, KSL003_POSITIVE))


def test_ksl003_negative(tmp_path):
    assert "KSL003" not in _rules_hit(_lint_source(tmp_path, KSL003_NEGATIVE))


def test_ksl003_noqa(tmp_path):
    src = KSL003_POSITIVE.replace(
        'prep = _Descent(x, None, "auto", 32768)',
        'prep = _Descent(x, None, "auto", 32768)  # ksel: noqa[KSL003] -- int-only path',
    )
    assert "KSL003" not in _rules_hit(_lint_source(tmp_path, src))


# ---------------------------------------------------------------------------
# KSL004 — raw clocks


KSL004_POSITIVE = """
    import time

    def bench(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0
"""


def test_ksl004_positive(tmp_path):
    report = _lint_source(tmp_path, KSL004_POSITIVE)
    assert len([f for f in report.unsuppressed if f.rule == "KSL004"]) == 2


def test_ksl004_negative_in_allowed_files(tmp_path):
    # the very same source inside utils/timing.py / utils/profiling.py is fine
    for allowed in ("utils/timing.py", "utils/profiling.py"):
        report = _lint_source(tmp_path, KSL004_POSITIVE, name=allowed)
        assert "KSL004" not in _rules_hit(report)


def test_ksl004_file_level_noqa(tmp_path):
    src = "# ksel: noqa-file[KSL004] -- perturb-chain fixture\n" + textwrap.dedent(
        KSL004_POSITIVE
    )
    report = _lint_source(tmp_path, src)
    assert not any(f.rule == "KSL000" for f in report.findings)  # parses
    assert "KSL004" not in _rules_hit(report)
    assert any(f.rule == "KSL004" and f.suppressed for f in report.findings)


# ---------------------------------------------------------------------------
# KSL005 — tier-1 membership (the generalized marker audit)


def _fake_tests_dir(tmp_path):
    d = tmp_path / "tests"
    d.mkdir()
    (d / "test_ok.py").write_text("def test_ok():\n    assert True\n")
    return d


def test_ksl005_positive(tmp_path):
    d = _fake_tests_dir(tmp_path)
    # module-level skip: collects nothing under -m 'not slow', no slow mark
    (d / "test_ghost.py").write_text(
        "import pytest\n"
        "pytest.importorskip('definitely_not_installed_xyz')\n"
        "def test_never_runs():\n    assert True\n"
    )
    report = run_analysis([d], contracts=False, select=["KSL005"])
    hits = [f for f in report.unsuppressed if f.rule == "KSL005"]
    assert len(hits) == 1 and "test_ghost.py" in hits[0].message


def test_ksl005_negative_slow_marked(tmp_path):
    d = _fake_tests_dir(tmp_path)
    (d / "test_heavy.py").write_text(
        "import pytest\n"
        "pytestmark = pytest.mark.slow\n"
        "def test_heavy():\n    assert True\n"
    )
    report = run_analysis([d], contracts=False, select=["KSL005"])
    assert "KSL005" not in _rules_hit(report)


def test_ksl005_file_noqa(tmp_path):
    d = _fake_tests_dir(tmp_path)
    (d / "test_ghost.py").write_text(
        "# ksel: noqa-file[KSL005] -- fixture: deliberately uncollected\n"
        "import pytest\n"
        "pytest.importorskip('definitely_not_installed_xyz')\n"
        "def test_never_runs():\n    assert True\n"
    )
    report = run_analysis([d], contracts=False, select=["KSL005"])
    assert "KSL005" not in _rules_hit(report)


# ---------------------------------------------------------------------------
# KSL006 — version-sensitive jax attrs outside utils/compat.py


KSL006_POSITIVE = """
    import jax
    from jax.experimental.shard_map import shard_map

    def build(fn, mesh, specs):
        jax.typeof(fn)
        with jax.enable_x64(False):
            pass
        return jax.shard_map(fn, mesh=mesh, in_specs=specs, out_specs=specs)
"""

KSL006_NEGATIVE = """
    from mpi_k_selection_tpu.utils import compat

    def build(fn, mesh, specs):
        compat.typeof(fn)
        with compat.enable_x64(False):
            pass
        return compat.shard_map(fn, mesh=mesh, in_specs=specs, out_specs=specs)
"""


def test_ksl006_positive(tmp_path):
    report = _lint_source(tmp_path, KSL006_POSITIVE)
    hits = [f for f in report.unsuppressed if f.rule == "KSL006"]
    assert len(hits) >= 4  # import + typeof + enable_x64 + shard_map


def test_ksl006_negative(tmp_path):
    assert "KSL006" not in _rules_hit(_lint_source(tmp_path, KSL006_NEGATIVE))


def test_ksl006_allowed_in_compat(tmp_path):
    report = _lint_source(tmp_path, KSL006_POSITIVE, name="utils/compat.py")
    assert "KSL006" not in _rules_hit(report)


# ---------------------------------------------------------------------------
# KSL007 — device_put in streaming/ without an explicit device/sharding


KSL007_POSITIVE = """
    import jax

    def stage(buf):
        data = jax.device_put(buf)
        data.block_until_ready()
        return data
"""

KSL007_NEGATIVE = """
    import jax

    def stage_committed(buf, device):
        return jax.device_put(buf, device)

    def stage_kw(buf, device):
        return jax.device_put(buf, device=device)

    def stage_sharded(buf, sharding):
        return jax.device_put(buf, sharding=sharding)

    def stage_default(buf):
        # an explicit None IS a declared target: the documented
        # single-slot default-device path
        return jax.device_put(buf, None)
"""


def test_ksl007_positive_in_streaming(tmp_path):
    report = _lint_source(tmp_path, KSL007_POSITIVE, name="streaming/stage.py")
    hits = [f for f in report.unsuppressed if f.rule == "KSL007"]
    assert len(hits) == 1 and "device" in hits[0].message


def test_ksl007_negative_explicit_targets(tmp_path):
    report = _lint_source(tmp_path, KSL007_NEGATIVE, name="streaming/stage.py")
    assert "KSL007" not in _rules_hit(report)


def test_ksl007_quiet_outside_streaming(tmp_path):
    # the rule gates the staged-ingest bug class, not device_put at large
    # (tpu_smoke/test code legitimately uses default-device puts)
    report = _lint_source(tmp_path, KSL007_POSITIVE, name="ops/stage.py")
    assert "KSL007" not in _rules_hit(report)


def test_ksl007_noqa(tmp_path):
    src = KSL007_POSITIVE.replace(
        "data = jax.device_put(buf)",
        "data = jax.device_put(buf)  # ksel: noqa[KSL007] -- fixture justification",
    )
    report = _lint_source(tmp_path, src, name="streaming/stage.py")
    assert "KSL007" not in _rules_hit(report)
    sup = [f for f in report.findings if f.rule == "KSL007" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL008 — raw file writes in streaming/ outside the spill store API


KSL008_POSITIVE = """
    import numpy as np

    def cache_chunk(path, keys):
        with open(path, "wb") as f:
            f.write(keys.tobytes())

    def cache_npy(path, keys):
        np.save(path, keys)

    def cache_tofile(path, keys):
        keys.tofile(path)

    def cache_pathlib(path, keys):
        import pathlib

        with pathlib.Path(path).open("wb") as f:
            f.write(keys.tobytes())
"""

KSL008_NEGATIVE = """
    import numpy as np

    def load_chunk(path):
        # reads are fine: the rule gates WRITES that dodge the record
        # keying/checksum/cleanup discipline
        with open(path, "rb") as f:
            return np.frombuffer(f.read(), np.uint32)

    def load_default_mode(path):
        return open(path).read()

    def load_pathlib(path):
        import pathlib

        with pathlib.Path(path).open("rb") as f:
            return f.read()
"""


def test_ksl008_positive_in_streaming(tmp_path):
    report = _lint_source(tmp_path, KSL008_POSITIVE, name="streaming/cache.py")
    hits = [f for f in report.unsuppressed if f.rule == "KSL008"]
    assert len(hits) == 4  # open/np.save/.tofile/Path(...).open
    assert all("spill store" in f.message for f in hits)


def test_ksl008_negative_reads_ok(tmp_path):
    report = _lint_source(tmp_path, KSL008_NEGATIVE, name="streaming/cache.py")
    assert "KSL008" not in _rules_hit(report)


def test_ksl008_quiet_outside_streaming_and_in_spill(tmp_path):
    # the rule scopes to streaming/ (bench/native/docs code writes files
    # legitimately) and exempts the sanctioned writer itself
    report = _lint_source(tmp_path, KSL008_POSITIVE, name="ops/cache.py")
    assert "KSL008" not in _rules_hit(report)
    report = _lint_source(tmp_path, KSL008_POSITIVE, name="streaming/spill.py")
    assert "KSL008" not in _rules_hit(report)


def test_ksl008_dynamic_open_mode_flagged(tmp_path):
    # a non-constant mode cannot be proven read-only: flag it
    src = """
    def cache(path, mode):
        return open(path, mode)
    """
    report = _lint_source(tmp_path, src, name="streaming/cache.py")
    assert "KSL008" in _rules_hit(report)


def test_ksl008_noqa(tmp_path):
    src = KSL008_POSITIVE.replace(
        "np.save(path, keys)",
        "np.save(path, keys)  # ksel: noqa[KSL008] -- fixture justification",
    )
    report = _lint_source(tmp_path, src, name="streaming/cache.py")
    hits = [f for f in report.unsuppressed if f.rule == "KSL008"]
    assert len(hits) == 3  # the other three writes still fire
    sup = [f for f in report.findings if f.rule == "KSL008" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL009 — print/logging telemetry in library code


KSL009_POSITIVE = """
    import logging

    logger = logging.getLogger("ksel")

    def descend(hist, k):
        print("pass done", k)
        logger.info("histogram total %s", int(hist.sum()))
        logging.warning("survivors: %d", k)
        return k
"""

KSL009_NEGATIVE = """
    import warnings

    def descend(hist, k, obs=None):
        if obs is not None:
            obs.emit(k)                      # structured telemetry channel
        if k < 0:
            raise ValueError("bad k")        # errors raise, not print
        if hist is None:
            warnings.warn("empty pass")      # warnings are sanctioned
        return k
"""


def test_ksl009_positive_in_library(tmp_path):
    report = _lint_source(
        tmp_path, KSL009_POSITIVE, name="mpi_k_selection_tpu/streaming/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL009"]
    # print + logger.info + logging.warning + logging.getLogger
    assert len(hits) == 4
    assert any("print" in f.message for f in hits)
    assert any("getLogger" in f.message for f in hits)


def test_ksl009_negative_obs_and_warnings_ok(tmp_path):
    report = _lint_source(
        tmp_path, KSL009_NEGATIVE, name="mpi_k_selection_tpu/streaming/mod.py"
    )
    assert "KSL009" not in _rules_hit(report)


def test_ksl009_quiet_outside_library_and_in_reporters(tmp_path):
    # bench/driver code outside the package prints legitimately
    report = _lint_source(tmp_path, KSL009_POSITIVE, name="bench_tool.py")
    assert "KSL009" not in _rules_hit(report)
    # the CLI and reporter surfaces are the sanctioned output layers
    for exempt in (
        "mpi_k_selection_tpu/cli.py",
        "mpi_k_selection_tpu/__main__.py",
        "mpi_k_selection_tpu/analysis/reporters.py",
        "mpi_k_selection_tpu/utils/timing.py",
    ):
        report = _lint_source(tmp_path, KSL009_POSITIVE, name=exempt)
        assert "KSL009" not in _rules_hit(report), exempt
    # test files poke stdout freely (named test_* per _is_test_file; kept
    # OUT of a tests/ dir so KSL005's collect-only probe stays untriggered)
    report = _lint_source(
        tmp_path, KSL009_POSITIVE, name="mpi_k_selection_tpu/test_mod.py"
    )
    assert "KSL009" not in _rules_hit(report)


def test_ksl009_noqa(tmp_path):
    src = KSL009_POSITIVE.replace(
        'print("pass done", k)',
        'print("pass done", k)  # ksel: noqa[KSL009] -- fixture justification',
    )
    report = _lint_source(
        tmp_path, src, name="mpi_k_selection_tpu/streaming/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL009"]
    assert len(hits) == 3  # the logging calls still fire
    sup = [f for f in report.findings if f.rule == "KSL009" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL010 — per-request compilation in serve/ handler paths


KSL010_POSITIVE = """
    import functools

    import jax

    def handle_query(x, ks):
        fn = jax.jit(lambda v: v[ks])          # fresh wrap per request
        factory = functools.partial(jax.jit, static_argnums=0)
        return fn(x)

    @jax.jit
    def handler_kernel(x):
        return x + 1
"""

KSL010_NEGATIVE = """
    def handle_query(registry, ds, ks):
        # dispatch through the keyed program cache: no compile wrap here
        fn = registry.programs.get_or_build(
            ("walk", ds.dataset_id, len(ks)),
            lambda: registry.build_walk(ds),
        )
        return fn(ks)
"""


def test_ksl010_positive_in_serve(tmp_path):
    report = _lint_source(
        tmp_path, KSL010_POSITIVE, name="mpi_k_selection_tpu/serve/handlers.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL010"]
    # jax.jit call + partial(jax.jit, ...) factory + @jax.jit decorator
    assert len(hits) == 3
    assert any("ProgramCache" in f.message for f in hits)


def test_ksl010_negative_cached_dispatch_ok(tmp_path):
    report = _lint_source(
        tmp_path, KSL010_NEGATIVE, name="mpi_k_selection_tpu/serve/server.py"
    )
    assert "KSL010" not in _rules_hit(report)


def test_ksl010_quiet_in_registry_outside_serve_and_tests(tmp_path):
    # the registry IS the sanctioned compilation surface
    report = _lint_source(
        tmp_path, KSL010_POSITIVE, name="mpi_k_selection_tpu/serve/registry.py"
    )
    assert "KSL010" not in _rules_hit(report)
    # jit anywhere else in the package is KSL010-quiet (other rules own it)
    report = _lint_source(
        tmp_path, KSL010_POSITIVE, name="mpi_k_selection_tpu/ops/mod.py"
    )
    assert "KSL010" not in _rules_hit(report)
    # test files poke jit freely
    report = _lint_source(
        tmp_path, KSL010_POSITIVE, name="mpi_k_selection_tpu/serve/test_mod.py"
    )
    assert "KSL010" not in _rules_hit(report)


def test_ksl010_noqa(tmp_path):
    src = KSL010_POSITIVE.replace(
        "fn = jax.jit(lambda v: v[ks])          # fresh wrap per request",
        "fn = jax.jit(lambda v: v[ks])  # ksel: noqa[KSL010] -- fixture justification",
    )
    report = _lint_source(
        tmp_path, src, name="mpi_k_selection_tpu/serve/handlers.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL010"]
    assert len(hits) == 2  # the factory + the decorator still fire
    sup = [f for f in report.findings if f.rule == "KSL010" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL011 — eager device gathers on streaming chunk-consume paths


KSL011_POSITIVE = """
    import numpy as np
    import jax

    def consume_chunk(kv, m, writer):
        surv = np.asarray(kv[m])               # eager boolean gather
        head = jax.device_get(kv[:128])        # eager slice transfer
        if surv.size:
            writer.append(surv)
"""

KSL011_NEGATIVE = """
    import numpy as np

    def consume_chunk(kv, handle, executor, kdt):
        keys = np.asarray(kv)                  # whole-array, not a gather
        surv = kv[keys > 0]                    # host indexing (numpy in, numpy out)
        executor.push(handle)                  # deferral: no sync here
        return np.asarray([1, 2], kdt)         # literal, not a subscript
"""


def test_ksl011_positive_in_streaming(tmp_path):
    report = _lint_source(
        tmp_path, KSL011_POSITIVE,
        name="mpi_k_selection_tpu/streaming/consume.py",
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL011"]
    assert len(hits) == 2  # np.asarray(kv[m]) + jax.device_get(kv[:128])
    assert any("deferred compaction" in f.message for f in hits)


def test_ksl011_negative_non_gather_asarray_ok(tmp_path):
    report = _lint_source(
        tmp_path, KSL011_NEGATIVE,
        name="mpi_k_selection_tpu/streaming/consume.py",
    )
    assert "KSL011" not in _rules_hit(report)


def test_ksl011_quiet_in_executor_outside_streaming_and_tests(tmp_path):
    # the executor owns the (deferred=off oracle) eager gather
    report = _lint_source(
        tmp_path, KSL011_POSITIVE,
        name="mpi_k_selection_tpu/streaming/executor.py",
    )
    assert "KSL011" not in _rules_hit(report)
    # the same pattern outside streaming/ is KSL011-quiet (KSL001 owns
    # the jit-reachable variant)
    report = _lint_source(
        tmp_path, KSL011_POSITIVE, name="mpi_k_selection_tpu/ops/mod.py"
    )
    assert "KSL011" not in _rules_hit(report)
    # test files poke chunks freely
    report = _lint_source(
        tmp_path, KSL011_POSITIVE,
        name="mpi_k_selection_tpu/streaming/test_mod.py",
    )
    assert "KSL011" not in _rules_hit(report)


def test_ksl011_noqa(tmp_path):
    src = KSL011_POSITIVE.replace(
        "surv = np.asarray(kv[m])               # eager boolean gather",
        "surv = np.asarray(kv[m])  # ksel: noqa[KSL011] -- fixture justification",
    )
    report = _lint_source(
        tmp_path, src, name="mpi_k_selection_tpu/streaming/consume.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL011"]
    assert len(hits) == 1  # the device_get gather still fires
    sup = [f for f in report.findings if f.rule == "KSL011" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL012 — silent broad excepts in streaming//serve//faults/; raw time.sleep


KSL012_POSITIVE = """
    import time

    def consume(chunk):
        try:
            return chunk.sum()
        except Exception:
            return None            # swallowed: no raise, value unused

    def pull(src):
        try:
            return next(src)
        except:
            pass                   # bare AND silent

    def backoff():
        time.sleep(0.5)            # raw wait outside the sleeper
"""

KSL012_NEGATIVE = """
    def transported(q, item):
        try:
            return item.run()
        except BaseException as e:
            item.error = e         # the value is transported, not dropped
            item.done.set()

    def reraised(x):
        try:
            return x()
        except Exception as e:
            if transient(e):
                raise RetryExhaustedError("gave up") from e
            raise

    def typed_only(x):
        try:
            return x()
        except ValueError:
            return None            # narrow except: not this rule's class
"""


def test_ksl012_positive_in_streaming(tmp_path):
    report = _lint_source(
        tmp_path, KSL012_POSITIVE,
        name="mpi_k_selection_tpu/streaming/consume.py",
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL012"]
    # the two silent handlers + the raw sleep
    assert len(hits) == 3
    assert any("time.sleep" in f.message for f in hits)
    assert any("swallows" in f.message for f in hits)


def test_ksl012_positive_in_serve_and_faults(tmp_path):
    for name in (
        "mpi_k_selection_tpu/serve/handler.py",
        "mpi_k_selection_tpu/faults/extra.py",
    ):
        report = _lint_source(tmp_path, KSL012_POSITIVE, name=name)
        assert "KSL012" in _rules_hit(report), name


def test_ksl012_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL012_NEGATIVE,
        name="mpi_k_selection_tpu/serve/batcher2.py",
    )
    assert "KSL012" not in _rules_hit(report)


def test_ksl012_scope(tmp_path):
    # broad excepts OUTSIDE the resilience layers are other rules' turf
    # (native loaders, backend probes legitimately feature-test), but the
    # sleep discipline is package-wide
    report = _lint_source(
        tmp_path, KSL012_POSITIVE, name="mpi_k_selection_tpu/native/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL012"]
    assert len(hits) == 1 and "time.sleep" in hits[0].message
    # the sleeper module owns time.sleep
    report = _lint_source(
        tmp_path,
        "import time\n\ndef s(x):\n    time.sleep(x)\n",
        name="mpi_k_selection_tpu/faults/sleeper.py",
    )
    assert "KSL012" not in _rules_hit(report)
    # tests simulate slow sources freely
    report = _lint_source(
        tmp_path, KSL012_POSITIVE,
        name="mpi_k_selection_tpu/streaming/test_mod.py",
    )
    assert "KSL012" not in _rules_hit(report)
    # outside the package entirely: quiet
    report = _lint_source(tmp_path, KSL012_POSITIVE, name="scripts/mod.py")
    assert "KSL012" not in _rules_hit(report)


def test_ksl012_noqa(tmp_path):
    src = KSL012_POSITIVE.replace(
        "        except Exception:",
        "        except Exception:  # ksel: noqa[KSL012] -- fixture justification",
    )
    report = _lint_source(
        tmp_path, src, name="mpi_k_selection_tpu/streaming/consume.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL012"]
    assert len(hits) == 2  # the bare except and the sleep still fire
    sup = [f for f in report.findings if f.rule == "KSL012" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL013 — unbounded metric label cardinality


KSL013_POSITIVE = """
    def per_chunk(reg, chunks):
        for i, chunk in enumerate(chunks):
            reg.counter("ingest.chunks", labels={"chunk": i}).inc()
            reg.gauge("chunk.bytes", labels={"idx": str(i)}).set(chunk.nbytes)

    def per_request(reg, requests):
        sizes = [
            reg.histogram("req.size", labels={"rid": f"{r.id}"}).observe(r.n)
            for r in requests
        ]
        return sizes
"""

KSL013_NEGATIVE = """
    def bounded(reg, phase, requests):
        # a function parameter is the CALLER's (closed) choice
        reg.gauge("phase.seconds", labels={"phase": phase}).set(1.0)
        # constant labels are the common case
        reg.counter("ingest.chunks", labels={"device": "host"}).inc()
        for r in requests:
            # per-occurrence data in the VALUE, labels constant
            reg.histogram("req.size", labels={"op": "kselect"}).observe(r.n)
        lab = {"device": str(len(requests))}
        # a labels= NAME built elsewhere is out of this rule's scope
        reg.counter("ingest.bytes", labels=lab).inc()
"""


def test_ksl013_positive_in_package(tmp_path):
    report = _lint_source(
        tmp_path, KSL013_POSITIVE,
        name="mpi_k_selection_tpu/obs/mod.py",
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL013"]
    # the two for-loop labels + the comprehension label
    assert len(hits) == 3
    assert all("unbounded label cardinality" in f.message for f in hits)


def test_ksl013_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL013_NEGATIVE,
        name="mpi_k_selection_tpu/obs/mod.py",
    )
    assert "KSL013" not in _rules_hit(report)


def test_ksl013_scope(tmp_path):
    # outside the package: a user script may label however it wants
    report = _lint_source(tmp_path, KSL013_POSITIVE, name="scripts/mod.py")
    assert "KSL013" not in _rules_hit(report)
    # tests simulate cardinality explosions on purpose
    report = _lint_source(
        tmp_path, KSL013_POSITIVE,
        name="mpi_k_selection_tpu/obs/test_mod.py",
    )
    assert "KSL013" not in _rules_hit(report)


def test_ksl013_noqa(tmp_path):
    src = KSL013_POSITIVE.replace(
        'reg.counter("ingest.chunks", labels={"chunk": i}).inc()',
        'reg.counter("ingest.chunks", labels={"chunk": i}).inc()'
        "  # ksel: noqa[KSL013] -- fixture justification",
    )
    report = _lint_source(
        tmp_path, src, name="mpi_k_selection_tpu/obs/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL013"]
    assert len(hits) == 2  # the gauge + the comprehension still fire
    sup = [f for f in report.findings if f.rule == "KSL013" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL014 — multiple ingest programs against one staged bucket per pass


KSL014_POSITIVE = """
    import numpy as np

    def run_pass(staged, specs, kdt):
        h = dispatch_chunk_histograms(staged, 16, 8, [0, 3], "scatter", kdt)
        c = dispatch_compaction(staged, specs, kdt, 32)   # second read
        return h, c

    def deep_fold(staged):
        from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
        a = masked_radix_histogram(staged.data, shift=16, radix_bits=16)
        b = masked_radix_histogram(staged.data, shift=0, radix_bits=16)
        return a, b
"""

KSL014_NEGATIVE = """
    def run_pass(staged, other, specs, kdt):
        # ONE ingest program per staged chunk is the sanctioned shape
        h = dispatch_chunk_histograms(staged, 16, 8, [0, 3], "scatter", kdt)
        # a DIFFERENT chunk's program is not a re-read of this bucket
        c = dispatch_compaction(other, specs, kdt, 32)
        return h, c

    def fused_pass(staged, specs, kdt):
        # the fused single-read program IS one program
        return dispatch_fused_ingest(staged, kdt=kdt, total_bits=32,
                                     collect_specs=specs)
"""


def test_ksl014_positive_in_streaming(tmp_path):
    report = _lint_source(
        tmp_path, KSL014_POSITIVE,
        name="mpi_k_selection_tpu/streaming/passes.py",
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL014"]
    assert len(hits) == 2  # the second dispatch in each function
    assert all("re-reads the whole staged bucket" in f.message for f in hits)


def test_ksl014_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL014_NEGATIVE,
        name="mpi_k_selection_tpu/streaming/passes.py",
    )
    assert "KSL014" not in _rules_hit(report)


def test_ksl014_quiet_in_executor_outside_streaming_and_tests(tmp_path):
    # the executor owns the sanctioned (fused="off" oracle) bundle
    report = _lint_source(
        tmp_path, KSL014_POSITIVE,
        name="mpi_k_selection_tpu/streaming/executor.py",
    )
    assert "KSL014" not in _rules_hit(report)
    # outside streaming/ the histogram primitives compose freely (the
    # resident pass loops legitimately sweep one array many times)
    report = _lint_source(
        tmp_path, KSL014_POSITIVE, name="mpi_k_selection_tpu/ops/mod.py"
    )
    assert "KSL014" not in _rules_hit(report)
    # test files dispatch against staged buffers freely
    report = _lint_source(
        tmp_path, KSL014_POSITIVE,
        name="mpi_k_selection_tpu/streaming/test_mod.py",
    )
    assert "KSL014" not in _rules_hit(report)


KSL014_SWEEP_POSITIVE = """
    def run_pass(staged, specs, kdt):
        # the sweep program IS the one sanctioned read; a histogram
        # beside it re-reads the bucket
        s = dispatch_sweep_ingest(staged, kdt=kdt, collect_specs=specs)
        h = dispatch_chunk_histograms(staged, 16, 8, [0, 3], "scatter", kdt)
        return s, h

    def double_sweep(staged, kdt):
        a = dispatch_sweep_ingest(staged, kdt=kdt, vkey=5)
        b = sweep_ingest_core(staged.data, 7, hp, cs, cp, ts, tp, vk)
        return a, b
"""

KSL014_SWEEP_NEGATIVE = """
    def run_pass(staged, specs, kdt):
        # ONE sweep program per staged chunk is the sanctioned shape
        return dispatch_sweep_ingest(staged, kdt=kdt, collect_specs=specs,
                                     vkey=5, sketch_bits=16)

    def two_chunks(staged, other, kdt):
        # a DIFFERENT chunk's sweep is not a re-read of this bucket
        a = dispatch_sweep_ingest(staged, kdt=kdt, vkey=5)
        b = dispatch_sweep_ingest(other, kdt=kdt, vkey=5)
        return a, b
"""


def test_ksl014_recognizes_sweep_dispatchers(tmp_path):
    report = _lint_source(
        tmp_path, KSL014_SWEEP_POSITIVE,
        name="mpi_k_selection_tpu/streaming/passes.py",
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL014"]
    assert len(hits) == 2  # the second program in each function
    assert all("re-reads the whole staged bucket" in f.message for f in hits)


def test_ksl014_sweep_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL014_SWEEP_NEGATIVE,
        name="mpi_k_selection_tpu/streaming/passes.py",
    )
    assert "KSL014" not in _rules_hit(report)


def test_ksl014_sweep_noqa(tmp_path):
    src = KSL014_SWEEP_POSITIVE.replace(
        "h = dispatch_chunk_histograms(staged, 16, 8, [0, 3], \"scatter\", kdt)",
        "h = dispatch_chunk_histograms(staged, 16, 8, [0, 3], \"scatter\", kdt)"
        "  # ksel: noqa[KSL014] -- fixture justification",
    )
    report = _lint_source(
        tmp_path, src, name="mpi_k_selection_tpu/streaming/passes.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL014"]
    assert len(hits) == 1  # the double_sweep pair still fires
    sup = [f for f in report.findings if f.rule == "KSL014" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


def test_ksl014_noqa(tmp_path):
    src = KSL014_POSITIVE.replace(
        "c = dispatch_compaction(staged, specs, kdt, 32)   # second read",
        "c = dispatch_compaction(staged, specs, kdt, 32)"
        "  # ksel: noqa[KSL014] -- fixture justification",
    )
    report = _lint_source(
        tmp_path, src, name="mpi_k_selection_tpu/streaming/passes.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL014"]
    assert len(hits) == 1  # the deep_fold double sweep still fires
    sup = [f for f in report.findings if f.rule == "KSL014" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# jaxpr contract checks (KSC101-KSC103) self-tests


def test_contract_checks_all_pass_on_shipped_kernels():
    from mpi_k_selection_tpu.analysis.jaxpr_checks import CONTRACT_CHECKS

    assert {c.id for c in CONTRACT_CHECKS} >= {"KSC101", "KSC102", "KSC103"}
    for check in CONTRACT_CHECKS:
        findings = check.run()
        assert findings == [], f"{check.id}: {[f.message for f in findings]}"


def test_ksc101_detects_dtype_demotion():
    # a kernel that demotes would be caught by the same eval_shape probe
    import jax
    import jax.numpy as jnp
    import numpy as np

    def demoting_select(x, k):
        return jnp.sort(x.astype(jnp.float32))[k - 1]  # drops the input dtype

    out = jax.eval_shape(
        lambda x: demoting_select(x, 3), jax.ShapeDtypeStruct((64,), "int32")
    )
    assert np.dtype(out.dtype) != np.dtype("int32")  # the probe sees it


def test_ksc102_count_dtype_raises_without_x64():
    import jax

    from mpi_k_selection_tpu.ops.radix import select_count_dtype

    if jax.config.jax_enable_x64:
        pytest.skip("needs x64 off to exercise the refusal")
    with pytest.raises(ValueError):
        select_count_dtype(1 << 31)


def test_ksc103_trail_detects_structural_divergence():
    import jax
    import jax.numpy as jnp

    from mpi_k_selection_tpu.analysis.jaxpr_checks import _primitive_trail

    def unstable(x):
        # program structure keyed on n: the recompile-hazard pattern
        if x.shape[0] % 2:
            return jnp.sum(x) + jnp.max(x)
        return jnp.sum(x)

    t1 = _primitive_trail(jax.make_jaxpr(unstable)(jnp.zeros(4)))
    t2 = _primitive_trail(jax.make_jaxpr(unstable)(jnp.zeros(5)))
    assert t1 != t2

    def stable(x):
        return jnp.sum(x) * 2

    s1 = _primitive_trail(jax.make_jaxpr(stable)(jnp.zeros(4)))
    s2 = _primitive_trail(jax.make_jaxpr(stable)(jnp.zeros(5)))
    assert s1 == s2


def test_ksc_contracts_cover_streaming_ingest():
    """ROADMAP item: the double-buffer ingest path is on the contract
    grid — both KSC102 (counter widths across the device/host histogram
    boundary) and KSC103 (trail stability) trace it at two chunk sizes.
    The multi-device round robin added the sketch deep-fold program and
    the collect filter predicate to that grid."""
    from mpi_k_selection_tpu.analysis.jaxpr_checks import (
        _STREAMING_INGEST_SIZES,
        _streaming_collect_mask_cases,
        _streaming_ingest_cases,
    )

    cases = _streaming_ingest_cases()
    assert len(_STREAMING_INGEST_SIZES) == 2
    # single-prefix pass 0 + multi-prefix shared sweep + sketch deep fold
    assert len(cases) >= 3
    assert all("streaming" in label for _, label, *_ in cases)
    assert {path for path, *_ in cases} == {
        "mpi_k_selection_tpu/streaming/chunked.py",
        "mpi_k_selection_tpu/streaming/sketch.py",
    }
    masks = _streaming_collect_mask_cases()
    assert masks and all("collect" in label for _, label, *_ in masks)


def test_ksc103_streaming_ingest_trail_stable_across_chunk_sizes():
    """The property itself, independent of the check plumbing: the device
    ingest programs trace to identical primitive trails at the two pow2
    staging buckets (streaming/pipeline.py pads every staged chunk to its
    bucket, so these are the shapes the pipelined descent actually runs)."""
    import jax

    from mpi_k_selection_tpu.analysis.jaxpr_checks import (
        _primitive_trail,
        _streaming_ingest_cases,
    )

    for _, label, fn, dt, (n1, n2) in _streaming_ingest_cases():
        t1 = _primitive_trail(jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((n1,), dt)))
        t2 = _primitive_trail(jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((n2,), dt)))
        assert t1 == t2, label


def test_ksc102_streaming_host_merge_is_int64():
    """The host side of the KSC102 streaming boundary: per-chunk histograms
    handed to the cross-chunk merge are int64 for every route — host
    counting, device single-prefix, device multi-prefix, and the pipelined
    staged buffer (whose pad correction must also stay in int64)."""
    import numpy as np

    from mpi_k_selection_tpu.streaming.chunked import _chunk_histograms
    from mpi_k_selection_tpu.streaming.pipeline import stage_keys

    kdt = np.dtype(np.uint32)
    probe = np.arange(100, dtype=np.uint32)  # non-pow2: staged path pads
    for mk, method in [
        (lambda: probe, "numpy"),
        (lambda: probe, "scatter"),
        (lambda: stage_keys(probe), "scatter"),
    ]:
        single = _chunk_histograms(mk(), 24, 8, [None], method, kdt)
        multi = _chunk_histograms(mk(), 16, 8, [0, 3], method, kdt)
        for h in list(single.values()) + list(multi.values()):
            assert np.dtype(h.dtype) == np.dtype(np.int64)


# ---------------------------------------------------------------------------
# CLI + exit codes


def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert lint_main([str(clean), "--no-contracts"]) == 0
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import time\n\ndef f():\n    return time.perf_counter()\n")
    assert lint_main([str(dirty), "--no-contracts"]) == 1
    assert lint_main([str(dirty), "--no-contracts", "--ignore", "KSL004"]) == 0
    out = tmp_path / "report.json"
    assert (
        lint_main([str(dirty), "--no-contracts", "--json", "--output", str(out)]) == 1
    )
    data = json.loads(out.read_text())
    assert data["exit_code"] == 1
    assert any(f["rule"] == "KSL004" for f in data["findings"])
    capsys.readouterr()


@pytest.mark.parametrize(
    "rule,src,name",
    [
        ("KSL001", KSL001_POSITIVE, "mod.py"),
        ("KSL002", KSL002_POSITIVE, "mod.py"),
        ("KSL003", KSL003_POSITIVE, "mod.py"),
        ("KSL004", KSL004_POSITIVE, "mod.py"),
        ("KSL006", KSL006_POSITIVE, "mod.py"),
        ("KSL007", KSL007_POSITIVE, "streaming/mod.py"),
        ("KSL010", KSL010_POSITIVE, "serve/mod.py"),
    ],
)
def test_cli_exits_nonzero_on_each_positive_fixture(tmp_path, capsys, rule, src, name):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(src))
    assert lint_main([str(f), "--no-contracts", "--select", rule]) == 1
    capsys.readouterr()


def test_cli_exits_nonzero_on_ksl005_positive(tmp_path, capsys):
    d = _fake_tests_dir(tmp_path)
    (d / "test_ghost.py").write_text(
        "import pytest\n"
        "pytest.importorskip('definitely_not_installed_xyz')\n"
        "def test_never_runs():\n    assert True\n"
    )
    assert lint_main([str(d), "--no-contracts", "--select", "KSL005"]) == 1
    capsys.readouterr()


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("KSL001", "KSL005", "KSL006", "KSL007", "KSC101", "KSC103"):
        assert rid in out


def test_module_entry_point_runs():
    # `python -m mpi_k_selection_tpu.analysis` — the console-script twin
    r = subprocess.run(
        [sys.executable, "-m", "mpi_k_selection_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert r.returncode == 0 and "KSL001" in r.stdout


# ---------------------------------------------------------------------------
# regression tests for the analyzer's first-run findings (the fixes)


def test_kselect_rejects_host_int64_without_x64():
    # before the fix, jnp.asarray silently truncated host int64 to int32 and
    # kselect answered from the wrong values (returned 0 for values > 2^31)
    import jax
    import numpy as np

    import mpi_k_selection_tpu as ks

    if jax.config.jax_enable_x64:
        pytest.skip("needs x64 off to exercise the truncation guard")
    x = np.arange(10, dtype=np.int64) * (1 << 40)
    with pytest.raises(ValueError, match="64-bit"):
        ks.kselect(x, 5)
    with pytest.raises(ValueError, match="64-bit"):
        ks.quantiles(x, [0.5])
    with pytest.raises(ValueError, match="64-bit"):
        ks.median(x)
    with pytest.raises(ValueError, match="64-bit"):
        ks.batched_kselect(x.reshape(2, 5), 2)


def test_kselect_host_int64_exact_under_x64():
    import numpy as np

    from mpi_k_selection_tpu.utils import x64

    import mpi_k_selection_tpu as ks

    with x64.enable_x64():
        x = (np.arange(10, dtype=np.int64) - 3) * (1 << 40)
        got = int(ks.kselect(x, 5))
        assert got == int(np.sort(x)[4])


def test_quantiles_preserves_float64_exactness_route():
    # quantiles used a bare jnp.asarray, bypassing as_selection_array's
    # host-f64 routing; now both route identically
    import numpy as np

    from mpi_k_selection_tpu import api
    from mpi_k_selection_tpu.utils import x64

    x = np.random.default_rng(3).standard_normal(100)
    with x64.enable_x64():
        got = np.asarray(api.quantiles(x, [0.5, 0.9]))
        s = np.sort(x)
        want = s[[max(1, int(np.ceil(q * 100))) - 1 for q in (0.5, 0.9)]]
        np.testing.assert_array_equal(got, want)


def test_kselect_accepts_weak_typed_python_lists():
    # NumPy widens plain Python lists to int64/float64; that is not a
    # caller-declared width, so the truncation guard must NOT fire —
    # list inputs keep the historical weak-typed conversion
    import jax

    import mpi_k_selection_tpu as ks

    if jax.config.jax_enable_x64:
        pytest.skip("exercises the x64-off weak-typing path")
    assert int(ks.kselect([3, 1, 2], 2)) == 2
    # lower median: k = max(1, n//2) = 1 for n=3 (reference semantics)
    assert float(ks.median([3.5, 1.5, 2.5])) == 1.5
    assert float(ks.median([3.5, 1.5, 2.5, 4.5])) == 2.5
    import numpy as np

    got = np.asarray(ks.quantiles([4, 2, 1, 3], [0.5]))
    assert got.tolist() == [2]
    assert np.asarray(ks.batched_kselect([[3, 1, 2], [6, 5, 4]], 2)).tolist() == [2, 5]
    assert np.asarray(ks.batched_median([[3, 1, 2], [6, 5, 4]])).tolist() == [1, 4]


def test_kselect_host_float64_still_downcasts_off_tpu():
    # float64 is NumPy's default float dtype; with x64 off the documented
    # behavior off-TPU is a value-rounding downcast ("exact w.r.t. its
    # actual contents"), NOT an error — only 64-bit INTEGER inputs, whose
    # truncation corrupts bit patterns/order, hard-fail
    import jax
    import numpy as np

    import mpi_k_selection_tpu as ks

    if jax.config.jax_enable_x64 or jax.default_backend() == "tpu":
        pytest.skip("exercises the x64-off off-TPU downcast path")
    x = np.random.default_rng(5).standard_normal(257)  # float64
    got = float(ks.kselect(x, 100))
    want = float(np.sort(x.astype(np.float32))[99])
    assert got == want
    assert float(ks.median(x)) == float(np.sort(x.astype(np.float32))[max(1, 257 // 2) - 1])


def test_ksl000_honors_ignore(tmp_path):
    bad = tmp_path / "vendored.py"
    bad.write_text("print 'python2'\n")
    report = run_analysis([bad], contracts=False)
    assert [f.rule for f in report.unsuppressed] == ["KSL000"]
    report = run_analysis([bad], contracts=False, ignore=["KSL000"])
    assert report.unsuppressed == []


def test_ksl004_exemption_is_cwd_independent(monkeypatch):
    # invoking the lint from inside the package must still recognize
    # utils/timing.py by its resolved path, not a cwd-relative suffix
    monkeypatch.chdir(REPO / "mpi_k_selection_tpu" / "utils")
    report = run_analysis(["timing.py"], contracts=False, select=["KSL004"])
    assert report.unsuppressed == []


def test_ksl002_nested_def_reports_once(tmp_path):
    src = """
    import jax.numpy as jnp
    import numpy as np

    def outer(x):
        if x.dtype == np.int64:
            pass

        def inner(v):
            return jnp.asarray(v)

        return inner(x)
    """
    report = _lint_source(tmp_path, src)
    hits = [f for f in report.unsuppressed if f.rule == "KSL002"]
    assert len(hits) == 1


def test_lint_scan_skips_virtualenvs(tmp_path):
    from mpi_k_selection_tpu.analysis.core import iter_python_files

    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "ok.py").write_text("x = 1\n")
    for d in (".venv/lib/site-packages", "venv", ".tox/py310", "x.egg-info"):
        (tmp_path / d).mkdir(parents=True)
        (tmp_path / d / "third_party.py").write_text(
            "import time\n\ndef f():\n    return time.time()\n"
        )
    files = [f.name for f in iter_python_files([tmp_path])]
    assert files == ["ok.py"]
    report = run_analysis([tmp_path], contracts=False)
    assert report.unsuppressed == []


# ---------------------------------------------------------------------------
# KSL018 — obs event types live in obs/events.py AND in the documented
# event catalog (docs/OBSERVABILITY.md), both directions


KSL018_OUTSIDE = """
    import dataclasses
    from typing import ClassVar

    class ObsEvent:
        pass

    @dataclasses.dataclass(frozen=True)
    class RogueEvent(ObsEvent):
        kind: ClassVar[str] = "rogue.event"
        site: str
"""

KSL018_NEGATIVE = """
    import dataclasses
    from typing import ClassVar

    @dataclasses.dataclass(frozen=True)
    class ObsEvent:
        # base-less root: not an emitted type
        kind: ClassVar[str] = "root"

    @dataclasses.dataclass
    class NotFrozen(ObsEvent):
        kind: ClassVar[str] = "x.y"

    @dataclasses.dataclass(frozen=True)
    class NotAnEvent(ObsEvent):
        value: int

    class PlainClass(ObsEvent):
        kind = "no.dataclass"
"""

KSL018_EVENTS = """
    import dataclasses
    from typing import ClassVar

    class ObsEvent:
        pass

    @dataclasses.dataclass(frozen=True)
    class OneEvent(ObsEvent):
        kind: ClassVar[str] = "a.one"
        n: int

    @dataclasses.dataclass(frozen=True)
    class TwoEvent(ObsEvent):
        kind: ClassVar[str] = "b.two"
        n: int
"""


def _ksl018_doc(tmp_path, kinds):
    doc = tmp_path / "docs" / "OBSERVABILITY.md"
    doc.parent.mkdir(parents=True, exist_ok=True)
    rows = "\n".join(f"| `{k}` | stuff |" for k in kinds)
    doc.write_text(
        "# Observability\n\n## Event schema\n\n"
        "| kind | fields |\n|---|---|\n" + rows + "\n\n## Next section\n"
    )


def test_ksl018_event_type_outside_events_py(tmp_path):
    report = _lint_source(
        tmp_path, KSL018_OUTSIDE, name="mpi_k_selection_tpu/serve/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL018"]
    assert len(hits) == 1
    assert "RogueEvent" in hits[0].message
    assert "rogue.event" in hits[0].message


def test_ksl018_negative_shapes_pass(tmp_path):
    report = _lint_source(
        tmp_path, KSL018_NEGATIVE, name="mpi_k_selection_tpu/serve/mod.py"
    )
    assert "KSL018" not in _rules_hit(report)


def test_ksl018_outside_package_and_tests_exempt(tmp_path):
    report = _lint_source(tmp_path, KSL018_OUTSIDE, name="elsewhere/mod.py")
    assert "KSL018" not in _rules_hit(report)
    report = _lint_source(
        tmp_path, KSL018_OUTSIDE,
        name="mpi_k_selection_tpu/tests/test_mod.py", select=["KSL018"],
    )
    assert "KSL018" not in _rules_hit(report)


def test_ksl018_noqa_suppresses(tmp_path):
    src = KSL018_OUTSIDE.replace(
        "class RogueEvent(ObsEvent):",
        "class RogueEvent(ObsEvent):  # ksel: noqa[KSL018] -- fixture",
    )
    report = _lint_source(
        tmp_path, src, name="mpi_k_selection_tpu/serve/mod.py"
    )
    assert "KSL018" not in _rules_hit(report)


def test_ksl018_catalog_in_sync_passes(tmp_path):
    _ksl018_doc(tmp_path, ["a.one", "b.two"])
    report = _lint_source(
        tmp_path, KSL018_EVENTS, name="mpi_k_selection_tpu/obs/events.py"
    )
    assert "KSL018" not in _rules_hit(report)


def test_ksl018_catalog_drift_both_directions(tmp_path):
    # b.two defined but undocumented; stale.kind documented but undefined
    _ksl018_doc(tmp_path, ["a.one", "stale.kind"])
    report = _lint_source(
        tmp_path, KSL018_EVENTS, name="mpi_k_selection_tpu/obs/events.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL018"]
    assert len(hits) == 2
    msgs = " | ".join(f.message for f in hits)
    assert "b.two" in msgs and "no row" in msgs
    assert "stale.kind" in msgs and "stale schema row" in msgs


def test_ksl018_no_doc_tree_checks_location_only(tmp_path):
    # a fixture tree without docs/ exercises only the location half
    report = _lint_source(
        tmp_path, KSL018_EVENTS, name="mpi_k_selection_tpu/obs/events.py"
    )
    assert "KSL018" not in _rules_hit(report)


def test_ksl018_real_catalog_is_in_sync():
    """The shipped obs/events.py and docs/OBSERVABILITY.md agree, both
    directions (the gate also enforces this; this is the direct form)."""
    report = run_analysis(
        [REPO / "mpi_k_selection_tpu" / "obs" / "events.py"],
        contracts=False, select=["KSL018"],
    )
    assert report.unsuppressed == [], [
        f.render() for f in report.unsuppressed
    ]


# ---------------------------------------------------------------------------
# THE GATE: zero unsuppressed findings over the whole repository


def test_analyzer_gate_whole_repo():
    """Runs every AST rule + every jaxpr contract check over the shipped
    tree. Any unsuppressed finding fails tier-1 — fix it or suppress it
    with a written justification (# ksel: noqa[...] -- why)."""
    from mpi_k_selection_tpu.analysis import render_json

    report = run_analysis(
        [REPO], root=REPO, contracts=True,
        mods=shared_modules([REPO], root=REPO),
    )
    pathlib.Path("/tmp/kselect_lint.json").write_text(render_json(report))
    assert report.unsuppressed == [], (
        "unsuppressed kselect-lint findings (full report: "
        "/tmp/kselect_lint.json):\n"
        + "\n".join(f.render() for f in report.unsuppressed)
    )
    # the suppression ledger must carry written justifications
    unjustified = [
        f for f in report.findings if f.suppressed and not f.justification
    ]
    assert unjustified == [], (
        "suppressed without a justification (add `-- why` to the noqa):\n"
        + "\n".join(f.render() for f in unjustified)
    )
    # staleness audit: a noqa whose rule no longer fires is a dead ledger
    # entry — the gate WARNS (tests/test_concurrency.py keeps the shipped
    # tree at zero; this warning is the in-band nudge during development)
    if report.dead_suppressions:
        import warnings

        warnings.warn(
            "stale ksel noqa suppressions (rule no longer fires): "
            + ", ".join(
                f"{d['path']}:{d['line']}[{d['rule']}]"
                for d in report.dead_suppressions
            ),
            RuntimeWarning,
            stacklevel=2,
        )
