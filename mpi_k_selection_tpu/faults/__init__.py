"""Deterministic fault injection + resilience policies.

The reference's only failure story is ``MPI_Abort`` — any anomaly kills
the program. A system serving heavy traffic must *degrade* under faults
instead: retry the transient ones, rebuild from redundant state, shed
load, and keep every exactness guarantee intact through recovery. This
package is both halves of that story:

- **Injection** (plan.py, inject.py, sleeper.py): a seeded, frozen
  :class:`FaultPlan` — replayable from one integer — executed by a
  :class:`FaultInjector` at the real failure surfaces (chunk pull,
  staging ``device_put``, spill record write/read, the serve dispatch
  loop), with sleeper-backed stalls and REAL on-disk corruption so the
  production validation machinery (CRC32, size checks) trips exactly as
  it would in the wild. Armed via the :func:`inject` context manager;
  usable from tests, the gauntlet, and the CLI ``--chaos`` knob.
- **Policies** (policy.py): :class:`RetryPolicy` (bounded attempts,
  exponential backoff through the injectable
  :class:`~mpi_k_selection_tpu.faults.sleeper.Sleeper`),
  :func:`retry_call` (in-place retry), and :func:`resilient_source`
  (mid-pass re-pull for replayable chunk sources). Pass-level recovery —
  re-running a streamed pass from the previous spill generation, the
  corrupt-record re-read/rebuild ladder, the ENOSPC downgrade — lives
  with the descent (streaming/chunked.py) and consumes these policies.

Every fault, retry, shed and downgrade emits a typed
:class:`~mpi_k_selection_tpu.obs.events.FaultEvent` plus metrics through
the existing obs registry, and recovered runs are test-enforced
bit-identical to fault-free runs across the devices x depth x spill x
deferred grid (tests/test_faults.py). See docs/ROBUSTNESS.md for the
fault taxonomy and recovery semantics.
"""

from __future__ import annotations

from mpi_k_selection_tpu.errors import (
    RetryExhaustedError,
    SpillCapacityError,
    TransientError,
)
from mpi_k_selection_tpu.faults.inject import (
    FaultInjector,
    active_injector,
    apply_disk_fault,
    inject,
    maybe_fault,
)
from mpi_k_selection_tpu.faults.plan import (
    FAULT_KINDS,
    FAULT_SITES,
    FaultPlan,
    FaultSpec,
)
from mpi_k_selection_tpu.faults.policy import (
    DEFAULT_RETRY,
    DEFAULT_RETRYABLE,
    RetryPolicy,
    resilient_source,
    resolve_retry,
    retry_call,
)
from mpi_k_selection_tpu.faults.sleeper import (
    DEFAULT_SLEEPER,
    RealSleeper,
    Sleeper,
    VirtualSleeper,
    resolve_sleeper,
)

__all__ = [
    "DEFAULT_RETRY",
    "DEFAULT_RETRYABLE",
    "DEFAULT_SLEEPER",
    "FAULT_KINDS",
    "FAULT_SITES",
    "FaultInjector",
    "FaultPlan",
    "FaultSpec",
    "RealSleeper",
    "RetryExhaustedError",
    "RetryPolicy",
    "Sleeper",
    "SpillCapacityError",
    "TransientError",
    "VirtualSleeper",
    "active_injector",
    "apply_disk_fault",
    "inject",
    "maybe_fault",
    "resilient_source",
    "resolve_retry",
    "resolve_sleeper",
    "retry_call",
]
