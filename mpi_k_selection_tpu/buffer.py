"""DeviceVector — the TPU-native counterpart of the reference's L1 layer.

The reference's only data structure is the ``IntVector`` growable int array
(``vector.h:7-11``: ``{int size; int capacity; int *data}``) with an ADT API
(``vector.h:13-34``). XLA arrays have static shapes, so the TPU equivalent
keeps a fixed physical ``capacity`` and carries the logical ``size`` as a
traced scalar: a pytree of ``(data[capacity], size)`` that flows through jit,
with every operation masking on ``iota < size``. Elements past ``size`` are
dead storage, exactly like the C struct's unused capacity.

API correspondence (reference ``file:line`` -> here):

=====================================  =====================================
``VecNew``            vector.c:53-70   ``DeviceVector.new`` / ``from_array``
``VecAdd``            vector.c:73-91   ``add`` (see note on growth)
``VecDelete``         vector.c:96-105  garbage collection (no-op needed)
``VecErase``          vector.c:108-121 ``erase`` — faithful O(1)
                                       swap-with-last, order-destroying
``MinFind``/``MaxFind`` vector.c:123-159 ``min``/``max`` (masked reductions)
``AverageFind``       vector.c:162-171 ``sum`` — the reference function is
                                       misnamed and returns the sum
                                       (SURVEY.md §2.1); ``mean`` is the
                                       repaired version
``VecGetCapacity`` …  vector.c:175-192 ``capacity`` attr, ``size``,
                                       ``is_full``
``VecSet``/``VecGet`` vector.c:194-218 ``set``/``get`` (bounds-checked)
``VecSearch``         vector.c:220-235 ``search`` (masked argmax, not a
                                       serial scan)
``VecQuickSort``      vector.c:239-241 ``sort`` (``lax.sort`` with dead
                                       slots keyed to the order-maximum)
``VecQuickSort2``     vector.c:23-50   same ``sort`` — the hand-rolled
                                       quicksort's partition primitive lives
                                       on as the radix kernels (ops/)
``VecBinarySearch``   vector.c:249-258 ``binary_search`` (searchsorted)
``VecBinarySearch2``  vector.c:261-287 same (its linear fallback on miss is
                                       a reference quirk, not a capability)
``compact``           (repair)         ordered masked compaction — what the
                                       CGM discard phase should have used
                                       instead of ``VecErase`` (SURVEY §2.3)
=====================================  =====================================

Growth note: ``VecAdd`` reallocs ×2 when full (``vector.c:79-84``), but the
reference always preallocates exactly and never grows (SURVEY.md §2.1). Here
``add`` on a full vector grows the buffer ×2 *outside* jit (a concrete-size
Python-level operation, like realloc) and raises under tracing, where shapes
must be static.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu.utils import dtypes as _dt


def _is_traced(*vals) -> bool:
    return any(isinstance(v, jax.core.Tracer) for v in vals)


def _order_max_key(kdt):
    """All-ones key of the (unsigned) key dtype, computed host-side."""
    return np.array(~np.uint64(0)).astype(np.dtype(kdt))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class DeviceVector:
    """Fixed-capacity device array with a traced logical size. Immutable:
    every mutator returns a new DeviceVector (functional JAX style)."""

    data: jax.Array
    size: jax.Array  # int32 scalar, 0 <= size <= capacity

    # -- pytree protocol -------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.size), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    # -- constructors (VecNew, vector.c:53-70) ---------------------------
    @classmethod
    def new(cls, capacity: int, dtype=jnp.int32) -> "DeviceVector":
        return cls(jnp.zeros((capacity,), dtype), jnp.zeros((), jnp.int32))

    @classmethod
    def from_array(cls, x) -> "DeviceVector":
        x = jnp.asarray(x).ravel()
        return cls(x, jnp.asarray(x.shape[0], jnp.int32))

    # -- accessors (vector.c:175-192) ------------------------------------
    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def is_full(self):
        return self.size >= self.capacity

    def _mask(self):
        return jnp.arange(self.capacity) < self.size

    def to_array(self) -> jax.Array:
        """Live prefix as a plain array (concrete size only)."""
        if _is_traced(self.size):
            raise ValueError("to_array needs a concrete size; use .data/.size")
        return self.data[: int(self.size)]

    # -- append (VecAdd, vector.c:73-91) ---------------------------------
    def add(self, value) -> "DeviceVector":
        if not _is_traced(self.size) and int(self.size) >= self.capacity:
            # realloc x2 growth path (vector.c:79-84) — concrete sizes only
            grown = jnp.concatenate(
                [self.data, jnp.zeros((max(1, self.capacity),), self.data.dtype)]
            )
            return DeviceVector(grown, self.size)._append(value)
        return self._append(value)

    def _append(self, value) -> "DeviceVector":
        # traced append: writing past capacity is a silent clamp (XLA
        # dynamic_update_slice semantics); callers preallocate like the
        # reference does (kth-problem-seq.c:19)
        idx = jnp.clip(self.size, 0, self.capacity - 1)
        data = self.data.at[idx].set(jnp.asarray(value, self.data.dtype))
        return DeviceVector(data, jnp.minimum(self.size + 1, self.capacity))

    # -- erase (VecErase, vector.c:108-121) ------------------------------
    def erase(self, pos) -> "DeviceVector":
        """Faithful O(1) swap-with-last delete — destroys element order,
        exactly like the reference (used by its CGM discard sweeps,
        TODO-kth-problem-cgm.c:208/219; consequence in SURVEY.md §2.3)."""
        pos = jnp.asarray(pos, jnp.int32)
        last = jnp.clip(self.size - 1, 0, self.capacity - 1)
        valid = jnp.logical_and(pos >= 0, pos < self.size)
        data = self.data.at[jnp.where(valid, pos, last)].set(self.data[last])
        return DeviceVector(data, jnp.where(valid, self.size - 1, self.size))

    # -- ordered compaction (the TPU-native repair of the discard phase) --
    def compact(self, keep_mask) -> "DeviceVector":
        """Keep elements where ``keep_mask`` is True, preserving order —
        the static-shape replacement for the reference's VecErase discard
        sweeps: dead slots move to the tail, size shrinks."""
        keep = jnp.logical_and(jnp.asarray(keep_mask), self._mask())
        # stable argsort of (!keep) floats kept elements to the front in order
        order = jnp.argsort(jnp.logical_not(keep), stable=True)
        return DeviceVector(self.data[order], jnp.sum(keep, dtype=jnp.int32))

    # -- reductions (MinFind/MaxFind vector.c:123-159; AverageFind :162-171)
    def min(self):
        """Minimum of live elements (MinFind). Empty -> dtype max, a clean
        identity instead of the reference's -1-as-error-value conflation."""
        kdt = _dt.key_dtype(self.data.dtype)
        big = _dt.from_sortable_bits(jnp.asarray(_order_max_key(kdt)), self.data.dtype)
        return jnp.min(jnp.where(self._mask(), self.data, big))

    def max(self):
        small = _dt.from_sortable_bits(
            jnp.zeros((), _dt.key_dtype(self.data.dtype)), self.data.dtype
        )
        return jnp.max(jnp.where(self._mask(), self.data, small))

    def sum(self):
        """Sum of live elements — what the reference's ``AverageFind``
        actually computes (it never divides; SURVEY.md §2.1 bug note)."""
        zero = jnp.zeros((), self.data.dtype)
        return jnp.sum(jnp.where(self._mask(), self.data, zero))

    def mean(self):
        """The repaired AverageFind: a real mean over live elements."""
        n = jnp.maximum(self.size, 1)
        return self.sum() / n.astype(jnp.float32)

    # -- element access (VecSet/VecGet, vector.c:194-218) ----------------
    def get(self, i):
        """Bounds-checked read. Concrete out-of-range -> IndexError (the
        reference returns the -2 error code, conflating it with data)."""
        if not _is_traced(i, self.size):
            if not 0 <= int(i) < int(self.size):
                raise IndexError(f"get({i}) out of range [0, {int(self.size)})")
        return self.data[jnp.clip(jnp.asarray(i, jnp.int32), 0, self.capacity - 1)]

    def set(self, i, value) -> "DeviceVector":
        if not _is_traced(i, self.size):
            if not 0 <= int(i) < int(self.size):
                raise IndexError(f"set({i}) out of range [0, {int(self.size)})")
        i = jnp.clip(jnp.asarray(i, jnp.int32), 0, self.capacity - 1)
        return DeviceVector(
            self.data.at[i].set(jnp.asarray(value, self.data.dtype)), self.size
        )

    # -- search (VecSearch vector.c:220-235) -----------------------------
    def search(self, element, start_pos=0):
        """Index of the first live occurrence of ``element`` at or after
        ``start_pos``; -1 when absent. One masked argmax, not a serial scan."""
        idx = jnp.arange(self.capacity)
        hit = (
            (self.data == jnp.asarray(element, self.data.dtype))
            & self._mask()
            & (idx >= jnp.asarray(start_pos, jnp.int32))
        )
        first = jnp.argmax(hit)
        return jnp.where(jnp.any(hit), first.astype(jnp.int32), jnp.int32(-1))

    # -- sort (VecQuickSort vector.c:239-241 / VecQuickSort2 :23-50) -----
    def sort(self) -> "DeviceVector":
        """Ascending sort of the live prefix. Dead slots are keyed to the
        order-maximum so they sink to the tail; one ``lax.sort`` replaces
        both the libc-qsort wrapper and the hand-rolled quicksort."""
        keys = _dt.to_sortable_bits(self.data)
        keys = jnp.where(self._mask(), keys, _order_max_key(keys.dtype))
        _, data = jax.lax.sort_key_val(keys, self.data)
        return DeviceVector(data, self.size)

    # -- binary search (VecBinarySearch vector.c:249-258 / :261-287) -----
    def binary_search(self, element):
        """Index of ``element`` in a sorted live prefix; -1 when absent.
        (The reference's fallback-to-linear-scan on miss, vector.c:286, is a
        quirk, not a capability — searchsorted covers both.)"""
        keys = _dt.to_sortable_bits(self.data)
        keys = jnp.where(self._mask(), keys, _order_max_key(keys.dtype))
        e = _dt.to_sortable_bits(jnp.asarray(element, self.data.dtype))
        pos = jnp.searchsorted(keys, e)
        pos_c = jnp.clip(pos, 0, self.capacity - 1)
        found = jnp.logical_and(pos < self.size, keys[pos_c] == e)
        return jnp.where(found, pos.astype(jnp.int32), jnp.int32(-1))
