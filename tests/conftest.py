"""Test configuration: force an 8-device virtual CPU mesh.

The JAX analogue of running the reference under local ``mpirun -np P``
(SURVEY.md §4, "Multi-node without a cluster"): the collective/sharded paths
run on 8 virtual CPU devices so the full multi-chip code path executes
without TPU hardware. Must run before the first ``import jax``.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The machine's site customization (PYTHONPATH sitecustomize) pins
# jax_platforms to the real TPU; tests must run on the 8-device virtual CPU
# mesh regardless, so override the config directly as well.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
