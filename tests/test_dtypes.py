"""Order-preserving key transforms: round-trip + ordering vs NumPy sort."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_k_selection_tpu.utils import dtypes as dt
from mpi_k_selection_tpu.utils import x64

DTYPES_32 = [np.int32, np.uint32, np.float32, np.int16, np.uint16, np.int8, np.uint8]


def _sample(dtype, n=4097, seed=7):
    rng = np.random.default_rng(seed)
    dtype = np.dtype(dtype)
    if dtype.kind in "iu":
        info = np.iinfo(dtype)
        x = rng.integers(info.min, info.max, size=n, endpoint=True, dtype=dtype)
        # force extreme values in
        x[:4] = [info.min, info.max, 0, info.max - 1 if dtype.kind == "u" else -1]
        return x
    x = rng.standard_normal(n).astype(dtype) * dtype.type(100)
    x[:5] = [0.0, -0.0, np.finfo(dtype).max, np.finfo(dtype).min, 1.5]
    return x


@pytest.mark.parametrize("dtype", DTYPES_32)
def test_roundtrip(dtype):
    x = _sample(dtype)
    u = dt.to_sortable_bits(jnp.asarray(x))
    back = np.asarray(dt.from_sortable_bits(u, dtype))
    np.testing.assert_array_equal(back, x)


@pytest.mark.parametrize("dtype", DTYPES_32)
def test_order_preserved(dtype):
    x = _sample(dtype)
    u = np.asarray(dt.to_sortable_bits(jnp.asarray(x)))
    order_u = np.argsort(u, kind="stable")
    xs = np.sort(x, kind="stable")
    np.testing.assert_array_equal(x[order_u], xs)


def test_bfloat16_roundtrip_and_order():
    x = jnp.asarray(np.random.default_rng(3).standard_normal(513), dtype=jnp.bfloat16)
    u = dt.to_sortable_bits(x)
    back = dt.from_sortable_bits(u, jnp.bfloat16)
    assert bool(jnp.all(back == x))
    xs = np.asarray(jax.lax.sort(x).astype(jnp.float32))
    xu = np.asarray(x.astype(jnp.float32))[np.argsort(np.asarray(u), kind="stable")]
    np.testing.assert_array_equal(xu, xs)


def test_int64_requires_x64():
    assert not jax.config.jax_enable_x64
    with pytest.raises(ValueError, match="64-bit"):
        dt._require_x64(np.int64)


def test_int64_roundtrip_under_x64():
    with x64.enable_x64():
        x = jnp.asarray(
            np.random.default_rng(5).integers(-(2**62), 2**62, size=257, dtype=np.int64)
        )
        u = dt.to_sortable_bits(x)
        assert u.dtype == jnp.uint64
        back = np.asarray(dt.from_sortable_bits(u, np.int64))
        np.testing.assert_array_equal(back, np.asarray(x))
        order_u = np.argsort(np.asarray(u), kind="stable")
        np.testing.assert_array_equal(np.asarray(x)[order_u], np.sort(np.asarray(x)))
