"""Descent telemetry: structured events, metrics, cross-thread tracing.

The instrumentation substrate for the streaming vertical (and the future
resident query server): one :class:`Observability` bundle carries up to
three independent channels —

- **events** (obs/events.py): typed per-pass / per-chunk observations of
  the exact descent (active prefixes, survivor populations, bytes
  streamed, chunk->device assignment, spill generation sizes);
- **metrics** (obs/metrics.py): counters / gauges / histograms
  (StagingPool hits/misses, ``pipeline.stall`` seconds, InflightWindow
  occupancy, spilled bytes, chunks per device) with JSON and
  Prometheus-text exposition;
- **trace** (obs/trace.py): producer/consumer host spans exported as
  perfetto-loadable Chrome trace-event JSON, layered on
  :class:`~mpi_k_selection_tpu.utils.profiling.PhaseTimer`.

Everything is OFF by default: the streaming entry points take
``obs=None`` and guard every emission behind that check, and enabling any
channel is guaranteed not to change a single answer bit
(tests/test_obs.py enforces bit-equality over the devices x
pipeline_depth x spill grid). Usage::

    from mpi_k_selection_tpu import obs as obs_lib

    o = obs_lib.Observability.collecting()
    v = api.kselect_streaming(source, k, obs=o)
    o.events.of_kind("stream.pass")        # typed event stream
    o.metrics.render_prometheus()          # exposition text
    o.trace.write("trace.json")            # open in perfetto

CLI: ``--metrics-json`` / ``--trace-events`` (cli.py). Docs:
docs/OBSERVABILITY.md (event schema, metric catalog, perfetto how-to).
"""

from __future__ import annotations

from mpi_k_selection_tpu.obs.events import (
    CallbackSink,
    CertificateEvent,
    ChunkEvent,
    DistributedSelectEvent,
    EventSink,
    FaultEvent,
    ListSink,
    ObsEvent,
    RecompileStormEvent,
    ResidentSelectEvent,
    ServeBatchEvent,
    ServeQueryEvent,
    SketchPassEvent,
    SpillGenerationEvent,
    StreamPassEvent,
    check_stream_invariants,
)
from mpi_k_selection_tpu.obs.flight import (
    FlightRecorder,
    build_bundle,
    resolve_flight,
)
from mpi_k_selection_tpu.obs.ledger import (
    LEDGER,
    ProgramLedger,
    collect_ledger,
    ledger_dispatch,
    snapshot_delta,
)
from mpi_k_selection_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    collect_runtime,
)
from mpi_k_selection_tpu.obs.trace import Span, TraceRecorder
from mpi_k_selection_tpu.obs.windows import WindowedHistogram

__all__ = [
    "CallbackSink",
    "CertificateEvent",
    "ChunkEvent",
    "Counter",
    "DistributedSelectEvent",
    "EventSink",
    "FaultEvent",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "LEDGER",
    "ListSink",
    "MetricsRegistry",
    "Observability",
    "ObsEvent",
    "ProgramLedger",
    "RecompileStormEvent",
    "ResidentSelectEvent",
    "ServeBatchEvent",
    "ServeQueryEvent",
    "SketchPassEvent",
    "Span",
    "SpillGenerationEvent",
    "StreamPassEvent",
    "TraceRecorder",
    "WindowedHistogram",
    "build_bundle",
    "check_stream_invariants",
    "collect_ledger",
    "collect_runtime",
    "ledger_dispatch",
    "resolve_flight",
    "snapshot_delta",
]


class Observability:
    """The pluggable telemetry bundle the descent entry points accept as
    ``obs=``. Any subset of channels may be active; ``None`` channels
    cost one attribute check at each emission site.

    ``flight`` (obs/flight.py) is the fourth, postmortem channel: a
    bounded ring that retains the most recent events and spans so a
    fault can dump a debug bundle — it SHARES the event stream (every
    ``emit`` fans into it) rather than replacing any sink.

    All channels are thread-safe — the pipelined descent records from
    the producer and consumer threads concurrently.
    """

    def __init__(self, *, events=None, metrics=None, trace=None, flight=None):
        self.events = events
        self.metrics = metrics
        self.trace = trace
        self.flight = resolve_flight(flight) if flight is not None else None

    @classmethod
    def collecting(cls, *, flight=False) -> "Observability":
        """All three live channels on, in-memory: a ListSink, a fresh
        MetricsRegistry, and a TraceRecorder — the everything-enabled
        form tests, the gauntlet and tpu_smoke use. ``flight=True`` (or
        an int ring capacity / a FlightRecorder) adds the postmortem
        ring too."""
        return cls(
            events=ListSink(), metrics=MetricsRegistry(),
            trace=TraceRecorder(), flight=flight or None,
        )

    def emit(self, event: ObsEvent) -> None:
        """Send one event to the sink and the flight ring (no-op without
        either)."""
        if self.events is not None:
            self.events.emit(event)
        if self.flight is not None:
            self.flight.record_event(event)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        on = [
            name
            for name in ("events", "metrics", "trace", "flight")
            if getattr(self, name) is not None
        ]
        return f"Observability({', '.join(on) or 'all channels off'})"
