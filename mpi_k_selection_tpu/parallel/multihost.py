"""Multi-host distributed runtime (SURVEY.md §5 "distributed communication
backend", scaled past one host).

The reference scales with ``mpirun -np P`` on one machine or a cluster —
MPICH handles process bootstrap and transports. The JAX equivalents:

- process bootstrap -> :func:`initialize` (``jax.distributed.initialize``):
  every host starts the same SPMD program with a coordinator address; after
  init, ``jax.devices()`` spans all hosts' chips.
- transports        -> XLA collectives ride ICI within a slice and DCN
  across slices/hosts automatically, chosen per mesh axis.
- rank/world        -> :func:`process_index` / :func:`process_count`.

Mesh policy for selection workloads: communication per radix pass is one
``psum`` of bucket counts — a few hundred bytes — so unlike model
parallelism there is no locality-sensitive axis layout to get right; a flat
1-D ``'data'`` axis over every chip in the job is optimal
(:func:`make_global_mesh`). The hybrid helper
(:func:`make_hybrid_mesh`) still exposes an explicit (dcn, ici) factorization
for workloads that want per-host sub-reductions first.

Single-shot batch jobs need no elastic recovery (the reference's only
failure handling is the world-size abort, ``TODO-kth-problem-cgm.c:56-59``,
mirrored by ``require_distributed``); a failed host fails the job and the
job re-runs.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from mpi_k_selection_tpu.parallel.mesh import DATA_AXIS


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> None:
    """Join the multi-host job (``jax.distributed.initialize``). On single
    host or under managed launchers (GKE/Cloud TPU) all arguments are
    auto-detected and may be omitted."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def make_global_mesh(axis_name: str = DATA_AXIS) -> Mesh:
    """Flat 1-D mesh over every chip in the job (all hosts)."""
    return Mesh(np.array(jax.devices()), (axis_name,))


def make_hybrid_mesh(
    dcn_axis: str = "hosts", ici_axis: str = DATA_AXIS
) -> Mesh:
    """2-D (hosts, chips-per-host) mesh: reductions over ``ici_axis`` stay on
    ICI within each host/slice; the small cross-host combine rides DCN."""
    devices = jax.devices()
    nproc = jax.process_count()
    per_host = len(devices) // max(1, nproc)
    if per_host * nproc != len(devices):
        raise ValueError(
            f"{len(devices)} devices do not divide evenly over {nproc} hosts"
        )
    grid = np.array(devices).reshape(nproc, per_host)
    return Mesh(grid, (dcn_axis, ici_axis))


def host_local_result(value):
    """Fetch a replicated scalar result on every host (the analogue of the
    reference printing from rank 0 only — under SPMD every host holds it)."""
    return jax.device_get(value)
