"""Parallel host data plane: the multi-worker ingest pool.

The contract under test: ``ingest_workers`` trades host threads for
ingest throughput and NOTHING else. One sequential puller preserves
source order, a pool of ``ksel-ingest-*`` workers runs encode ->
spill-tee pack -> staging independently, and the reorder sequencer
releases finished chunks to the consumer strictly in chunk-index order
— so every answer is bit-identical at every pool width, spill records
land in pull order, seeded chaos replays identically, and ``1`` is
byte-for-byte the legacy single-producer path. The read side mirrors
it: ``SpillGeneration.iter_chunks(workers=N)`` decodes records on a
pool and yields them in index order.
"""

import threading

import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.streaming import (
    SpillStore,
    streaming_kselect,
    streaming_kselect_many,
)
from mpi_k_selection_tpu.streaming import pipeline as pl
from mpi_k_selection_tpu.streaming.chunked import resolve_width_schedule
from mpi_k_selection_tpu.streaming.pipeline import (
    INGEST_THREAD_PREFIX,
    INGEST_WORKERS_AUTO_CAP,
    MAX_INGEST_WORKERS,
    resolve_ingest_workers,
)


def _chunks(x, nchunks):
    return [np.ascontiguousarray(c) for c in np.array_split(x, nchunks)]


def _assert_no_ingest_threads():
    """Every pooled path joins its workers before returning: no
    ``ksel-ingest-*`` thread (encode pool or decode pool) survives."""
    leaked = [
        t.name
        for t in threading.enumerate()
        if t.name.startswith(INGEST_THREAD_PREFIX)
    ]
    assert not leaked, leaked


# -- the knob itself ----------------------------------------------------------


def test_resolve_ingest_workers_contract():
    """None -> legacy 1; 'auto' -> min(cap, cores); ints validate into
    [1, MAX]; bools and junk are refused loudly (a bool silently meaning
    0 or 1 workers is exactly the bug the isinstance guard exists for)."""
    import os

    assert resolve_ingest_workers(None) == 1
    auto = resolve_ingest_workers("auto")
    assert auto == min(INGEST_WORKERS_AUTO_CAP, os.cpu_count() or 1)
    assert 1 <= auto <= INGEST_WORKERS_AUTO_CAP
    assert resolve_ingest_workers(1) == 1
    assert resolve_ingest_workers(np.int64(3)) == 3
    assert resolve_ingest_workers(MAX_INGEST_WORKERS) == MAX_INGEST_WORKERS
    with pytest.raises(ValueError, match="out of range"):
        resolve_ingest_workers(0)
    with pytest.raises(ValueError, match="out of range"):
        resolve_ingest_workers(MAX_INGEST_WORKERS + 1)
    for junk in (True, False, 2.0, "three"):
        with pytest.raises(ValueError, match="ingest_workers"):
            resolve_ingest_workers(junk)


# -- bit-equality across the full grid ----------------------------------------


@pytest.mark.parametrize("fused", ["auto", "off"])
def test_pool_bit_equality_grid(fused, rng):
    """workers {1,2,4} x devices {1,2} x depth {0,2} x spill {off,force}:
    every leg is bit-identical to the workers=1 oracle — the reorder
    sequencer makes pool width invisible to the descent."""
    n = 1 << 13
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)
    ks = [1, 1337, n // 2, n]
    want = [np.asarray(seq.kselect_sort(x, k)).item() for k in ks]
    chunks = _chunks(x, 8)
    for devices in (1, 2):
        for depth in (0, 2):
            for spill in ("off", "force"):
                legs = {}
                for workers in (1, 2, 4):
                    got = streaming_kselect_many(
                        chunks, ks, pipeline_depth=depth, devices=devices,
                        spill=spill, collect_budget=256, fused=fused,
                        ingest_workers=workers,
                    )
                    legs[workers] = [np.asarray(g).item() for g in got]
                assert legs[1] == want, (devices, depth, spill)
                assert legs[2] == legs[1], (devices, depth, spill)
                assert legs[4] == legs[1], (devices, depth, spill)
    _assert_no_ingest_threads()


@pytest.mark.parametrize("dtype", [np.uint64, np.float64], ids=["u64", "f64"])
def test_pool_host_exact_bypass_dtypes(dtype, rng):
    """64-bit streams take the host-exact bypass (host histograms, no
    device counting) — the pool parallelizes their encode too, and the
    answer stays bit-identical, spilled or not."""
    n = 1 << 13
    if np.dtype(dtype).kind == "f":
        x = (rng.standard_normal(n) * 1e6).astype(dtype)
    else:
        x = rng.integers(0, 1 << 63, size=n, dtype=np.int64).astype(dtype)
    ks = [7, n // 2]
    want = [np.asarray(seq.kselect_sort(x, k)).item() for k in ks]
    for spill in ("off", "force"):
        for workers in (1, 4):
            got = streaming_kselect_many(
                _chunks(x, 8), ks, spill=spill, collect_budget=256,
                ingest_workers=workers,
            )
            assert [np.asarray(g).item() for g in got] == want, (spill, workers)
    _assert_no_ingest_threads()


def test_one_shot_source_under_pool(rng):
    """A one-shot generator source streams through a 4-wide pool: the
    sequential puller is the only consumer of the iterator (workers never
    touch it), so one-shot-ness is preserved exactly as at workers=1."""
    n = 1 << 13
    x = rng.integers(0, 1 << 62, size=n, dtype=np.int64).astype(np.uint64)
    want = seq.kselect_sort(x, 999)
    got = streaming_kselect(
        iter(_chunks(x, 8)), 999, spill="force", collect_budget=128,
        ingest_workers=4,
    )
    assert got == want
    _assert_no_ingest_threads()


def test_drifting_source_raises_with_workers_in_flight(rng):
    """A source that changes dtype mid-stream is refused by the
    sequential puller while pool workers are in flight: the abort fence
    poisons the pool, the TypeError propagates to the caller, and every
    worker thread is joined — nothing leaks on the raise path."""
    good = rng.integers(-1000, 1000, size=4096, dtype=np.int64).astype(np.int32)
    chunks = _chunks(good, 6)
    chunks[3] = chunks[3].astype(np.float32)  # drift after 3 clean chunks
    with pytest.raises(
        TypeError, match="streaming selection requires one dtype per stream"
    ):
        streaming_kselect(
            chunks, 17, spill="force", collect_budget=64, ingest_workers=4
        )
    _assert_no_ingest_threads()


# -- sequencer ordering under skewed work -------------------------------------


def test_sequencer_orders_spill_under_slow_worker(rng, tmp_path):
    """Chunk 0 is ~50x the later chunks, so with 4 workers the fast
    chunks finish encoding long before chunk 0's worker: the sequencer
    must hold them. Spill records are written at sequencer-release time,
    so their chunk_index order IS the release order — assert it equals
    pull order exactly, and the answer stays exact."""
    big = rng.integers(-(2**31), 2**31, size=100_000, dtype=np.int64)
    small = [
        rng.integers(-(2**31), 2**31, size=2048, dtype=np.int64)
        for _ in range(7)
    ]
    chunks = [c.astype(np.int32) for c in (big, *small)]
    x = np.concatenate(chunks)
    k = x.size // 2
    with SpillStore(str(tmp_path)) as store:
        got = streaming_kselect(
            chunks, k, spill=store, collect_budget=64, ingest_workers=4
        )
        assert got == seq.kselect_sort(x, k)
        gen0 = store.generations[min(store.generations)]
        assert [r.chunk_index for r in gen0.records] == list(range(len(chunks)))
    _assert_no_ingest_threads()


def test_seeded_chaos_bit_equality_at_four_workers(rng):
    """A seeded fault plan (stage + spill faults, virtual clock) replays
    identically at workers=4: fault indices are pre-assigned in pull
    order by the puller and fired at in-order write time, so WHICH
    attempt faults cannot depend on pool scheduling."""
    from mpi_k_selection_tpu import faults

    chunks = [
        rng.integers(-(2**31), 2**31 - 1, m, np.int64).astype(np.int32)
        for m in (5000, 4096, 2048, 3000)
    ]
    x = np.concatenate(chunks)
    k = x.size // 2
    want = int(np.sort(x, kind="stable")[k - 1])
    answers = []
    for workers in (1, 4):
        plan = faults.FaultPlan.seeded(23, n_chunks=len(chunks), faults=4)
        policy = faults.RetryPolicy(sleeper=faults.VirtualSleeper())
        with faults.inject(plan, sleeper=faults.VirtualSleeper()) as inj:
            got = streaming_kselect(
                inj.wrap_chunk_source(lambda: iter(chunks)), k,
                spill="force", devices=2, retry=policy, radix_bits=4,
                collect_budget=64, ingest_workers=workers,
            )
        answers.append(int(got))
    assert answers == [want, want]
    _assert_no_ingest_threads()


# -- the pooled spill read side -----------------------------------------------


@pytest.mark.parametrize("mmap", [False, True], ids=["read", "mmap"])
def test_pooled_decode_matches_serial(mmap, tmp_path, rng):
    """iter_chunks(workers=4) decodes on a pool but yields records in
    index order with bit-identical keys — plain, mmap'd, and under a
    segment filter (where filtered-empty records are skipped, shrinking
    the yielded list the same way the serial path shrinks it)."""
    keys = rng.integers(0, 1 << 63, size=30_000, dtype=np.int64).astype(np.uint64)
    store = SpillStore(str(tmp_path))
    w = store.new_generation(pack_digit_bits=8)
    for part in np.array_split(keys, 7):
        w.append(part, np.uint64)
    gen = w.commit()
    serial = list(gen.iter_chunks(mmap=mmap))
    pooled = list(gen.iter_chunks(mmap=mmap, workers=4))
    assert [c.chunk_index for c in pooled] == [c.chunk_index for c in serial]
    for s, p in zip(serial, pooled):
        np.testing.assert_array_equal(s.keys, p.keys)
    top = int(keys[0] >> np.uint64(60))
    specs = ((4, top),)
    serial_f = list(gen.iter_chunks(filter_specs=specs))
    pooled_f = list(gen.iter_chunks(filter_specs=specs, workers=4))
    assert [c.chunk_index for c in pooled_f] == [
        c.chunk_index for c in serial_f
    ]
    for s, p in zip(serial_f, pooled_f):
        np.testing.assert_array_equal(s.keys, p.keys)
    store.close()
    _assert_no_ingest_threads()


def test_pooled_decode_propagates_corruption(tmp_path, rng):
    """A corrupt record raises through the pool exactly as it does
    serially, and the decode workers are joined on the raise path."""
    from mpi_k_selection_tpu.streaming.spill import SpillRecordError

    keys = rng.integers(0, 1 << 62, size=8192, dtype=np.int64).astype(np.uint64)
    store = SpillStore(str(tmp_path))
    w = store.new_generation(pack_digit_bits=8)
    for part in np.array_split(keys, 4):
        w.append(part, np.uint64)
    gen = w.commit()
    rec = gen.records[2]
    data = bytearray(open(rec.path, "rb").read())
    data[-2] ^= 0xFF  # a byte inside the last segment's payload
    with open(rec.path, "wb") as f:
        f.write(data)
    with pytest.raises(SpillRecordError):
        list(gen.iter_chunks(workers=4))
    _assert_no_ingest_threads()
    store.close()


# -- the 64-bit two-wide-pass width schedule ----------------------------------


def test_width_schedule_auto_two_wide_passes():
    """64-bit keys get a SECOND strictly-wide pass: auto at rb=8 is
    (16, 16, 8x4); every width respects MAX_PASS_BITS and the KSC102
    counter budget independently; 32-bit schedules are untouched; a
    sketch-seeded 64-bit start below the 32-bit threshold stays
    single-wide."""
    from mpi_k_selection_tpu.streaming.chunked import MAX_PASS_BITS

    s64 = resolve_width_schedule("auto", 64, 8)
    assert s64 == (16, 16, 8, 8, 8, 8)
    assert sum(s64) == 64 and all(1 <= w <= MAX_PASS_BITS for w in s64)
    assert resolve_width_schedule("auto", 64, 4) == (16, 16) + (4,) * 8
    # 32-bit: one wide pass only, exactly as before the 64-bit rule
    assert resolve_width_schedule("auto", 32, 8) == (16, 8, 8)
    assert resolve_width_schedule("auto", 32, 4) == (16, 4, 4, 4, 4)
    # seeded start with <= 32 bits remaining: the second-pass rule never
    # fires (remaining <= 32), even on a 64-bit stream
    seeded = resolve_width_schedule("auto", 64, 8, start_bits=32)
    assert seeded == (16, 8, 8)


def test_two_wide_pass_descent_bit_identical(rng):
    """The (16, 16, ...) schedule on real uint64 streams: bit-identical
    to the legacy fixed schedule across spill x workers, with the
    explicit tuple equal to what auto resolves."""
    n = 1 << 13
    x = rng.integers(0, 1 << 63, size=n, dtype=np.int64).astype(np.uint64)
    ks = [1, 999, n // 2, n]
    want = [np.asarray(seq.kselect_sort(x, k)).item() for k in ks]
    chunks = _chunks(x, 8)
    for spill in ("off", "force"):
        for schedule in ("auto", "off", (16, 16, 8, 8, 8, 8)):
            for workers in (1, 4):
                got = streaming_kselect_many(
                    chunks, ks, spill=spill, collect_budget=256,
                    width_schedule=schedule, pack_spill="auto",
                    ingest_workers=workers,
                )
                assert [np.asarray(g).item() for g in got] == want, (
                    spill, schedule, workers,
                )
    _assert_no_ingest_threads()


# -- observability ------------------------------------------------------------


def test_seq_wait_phase_accounting(rng):
    """The sequencer-stall phase exists in the phase vocabulary but not
    in INGEST_PHASES (it measures coordination, not work — folding it
    into work would understate encode_hidden_frac), and
    encode_hidden_frac clamps into [0, 1] / returns None on no work."""
    assert pl.SEQ_WAIT_PHASE == "pipeline.seq_wait"
    assert pl.SEQ_WAIT_PHASE not in pl.INGEST_PHASES
    assert {"pipeline.encode", "pipeline.pack", "pipeline.stage"} <= set(
        pl.INGEST_PHASES
    )

    class _T:
        def __init__(self, phases):
            self.phases = phases

    assert pl.encode_hidden_frac(_T({})) is None
    full = {p: 1.0 for p in pl.INGEST_PHASES}
    assert pl.encode_hidden_frac(_T(full)) == 1.0
    stalled = dict(full, **{pl.STALL_PHASE: 100.0})
    assert pl.encode_hidden_frac(_T(stalled)) == 0.0
