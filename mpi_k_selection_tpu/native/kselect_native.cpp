// Native runtime for the k-selection framework.
//
// Two components, mirroring the reference's two compiled programs:
//
// 1. nth_element_*: the sequential oracle engine — the compiled equivalent
//    of the reference's `seq` binary (kth-problem-seq.c sort-then-index,
//    done with introselect instead of a full qsort).
//
// 2. cgm_kselect_i32: the distributed CGM weighted-median k-selection of
//    TODO-kth-problem-cgm.c:35-296, re-implemented as P forked OS processes
//    communicating through a POSIX shared-memory control block — the
//    in-tree stand-in for the MPICH runtime (libmpi.so.12) the reference
//    links. Collective correspondence:
//
//      MPI_Scatterv (:103)   -> each child copies its balanced block
//                               (:81-100 partitioning) out of the parent's
//                               copy-on-write pages into a private shard
//      MPI_Gather  (:135-136)-> per-rank slots in the control block + barrier
//      MPI_Bcast   (:168)    -> root writes the pivot slot + barrier
//      MPI_Allreduce (:190)  -> per-rank (l,e,g) slots + barrier + local sum
//      MPI_Barrier (:269)    -> pthread_barrier_t (PTHREAD_PROCESS_SHARED)
//      MPI_Gatherv (:270)    -> shared survivor arena with displacements
//                               computed from gathered counts (:245-266)
//
//    Deliberate repairs over the reference (SURVEY.md §2.3): shards stay
//    sorted and discards narrow a [lo,hi) window (the reference's VecErase
//    swap-delete scrambled order, degrading its pivots); the use-after-free
//    around the final Gatherv (:250-270) has no analogue here; counters are
//    64-bit so N > 2^31 cannot overflow (SURVEY.md §7).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <new>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr int kMaxProcs = 64;

template <typename T>
int nth_impl(const T* data, int64_t n, int64_t k, T* out) {
  if (!data || !out || n <= 0 || k < 1 || k > n) return 1;
  std::vector<T> buf(data, data + n);
  std::nth_element(buf.begin(), buf.begin() + (k - 1), buf.end());
  *out = buf[k - 1];
  return 0;
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

struct Ctrl {
  pthread_barrier_t barrier;
  int64_t meds[kMaxProcs];
  int64_t cnts[kMaxProcs];
  int64_t leg[kMaxProcs][3];
  int64_t pivot;
  int64_t surv_cnt[kMaxProcs];
  int32_t answer;
  int32_t found;
  int64_t rounds;
  double elapsed;
  int32_t error;
};

// One SPMD rank of the CGM protocol (the body of main(), TODO-…:35-296).
void cgm_rank(int r, int p, const int32_t* input, int64_t n, int64_t k,
              int64_t c, Ctrl* ctl, int32_t* arena) {
  // balanced block partition: first n%p ranks get one extra (TODO-…:81-100)
  const int64_t base = n / p, rem = n % p;
  const int64_t sz = base + (r < rem ? 1 : 0);
  const int64_t off = r * base + std::min<int64_t>(r, rem);

  double t0 = now_s();  // MPI_Wtime after generation (:76)

  std::vector<int32_t> shard(input + off, input + off + sz);  // Scatterv :103
  std::sort(shard.begin(), shard.end());                      // qsort :115

  int64_t lo = 0, hi = sz;
  int64_t kk = k;
  int64_t N = n;
  bool found = false;
  int32_t answer = 0;
  int64_t rounds = 0;
  const int64_t threshold = std::max<int64_t>(1, n / (c * p));  // :122
  // true-median pivots discard >= N/4 per round; generous safety bound, the
  // post-loop gather path is exact for any surviving window anyway
  int64_t max_rounds = 64;
  for (int64_t m = n; m; m >>= 1) max_rounds += 8;

  while (N >= threshold && rounds < max_rounds) {
    // local median of the live window; even width averages the two middles
    // with int truncation, exactly like (:126) — pivot-only, never returned
    const int64_t w = hi - lo;
    int64_t med = INT64_MIN;  // empty shard: zero weight, value ignored
    if (w > 0) {
      med = (w % 2) ? shard[lo + w / 2]
                    : ((int64_t)shard[lo + w / 2 - 1] + shard[lo + w / 2]) / 2;
    }
    ctl->meds[r] = med;  // the two MPI_Gathers (:135-136), fused as the
    ctl->cnts[r] = w;    // author's TODO (:107-112) intended
    pthread_barrier_wait(&ctl->barrier);

    if (r == 0) {  // weighted median on the root (:139-165)
      int64_t M = 0;
      bool any = false;
      for (int i = 0; i < p && !any; i++)
        if (ctl->cnts[i] > 0) { M = ctl->meds[i]; any = true; }  // fallback :163
      for (int i = 0; i < p; i++) {
        if (ctl->cnts[i] == 0) continue;
        const int64_t mi = ctl->meds[i];
        int64_t min_sum = 0, max_sum = 0;
        for (int j = 0; j < p; j++) {
          if (ctl->meds[j] < mi) min_sum += ctl->cnts[j];
          else if (ctl->meds[j] > mi) max_sum += ctl->cnts[j];
        }
        if (2 * min_sum <= N && 2 * max_sum <= N) { M = mi; break; }
      }
      ctl->pivot = M;  // MPI_Bcast (:168)
    }
    pthread_barrier_wait(&ctl->barrier);
    const int64_t M = ctl->pivot;

    // local L/E/G (:170-185) — binary searches on the sorted window instead
    // of the reference's linear sweep
    const int64_t lb =
        std::lower_bound(shard.begin() + lo, shard.begin() + hi, M) -
        shard.begin();
    const int64_t ub =
        std::upper_bound(shard.begin() + lo, shard.begin() + hi, M) -
        shard.begin();
    ctl->leg[r][0] = lb - lo;
    ctl->leg[r][1] = ub - lb;
    ctl->leg[r][2] = hi - ub;
    pthread_barrier_wait(&ctl->barrier);  // MPI_Allreduce(SUM) (:190)
    int64_t L = 0, E = 0, G = 0;
    for (int i = 0; i < p; i++) {
      L += ctl->leg[i][0];
      E += ctl->leg[i][1];
      G += ctl->leg[i][2];
    }
    rounds++;

    if (L < kk && kk <= L + E) {  // exact-hit test (:194-201)
      found = true;
      answer = (int32_t)M;  // E >= 1 ensures M is an actual element value
      break;
    }
    if (kk <= L) {  // discard >= M (:204-213), as window narrowing
      hi = lb;
      N = L;
    } else {  // discard <= M (:215-225)
      lo = ub;
      N = G;
      kk -= L + E;
    }
    // every rank computed identical (M, L, E, G, N, kk): no barrier needed
    // before the next round's per-rank slot writes (meds/cnts != leg)
  }

  if (!found) {  // remainder path (:236-280): Gatherv survivors, solve on root
    ctl->surv_cnt[r] = hi - lo;
    pthread_barrier_wait(&ctl->barrier);  // the size gather (:242)
    int64_t disp = 0, total = 0;
    for (int i = 0; i < p; i++) {
      if (i < r) disp += ctl->surv_cnt[i];
      total += ctl->surv_cnt[i];
    }
    if (hi > lo)
      std::memcpy(arena + disp, shard.data() + lo, (hi - lo) * sizeof(int32_t));
    pthread_barrier_wait(&ctl->barrier);  // MPI_Barrier + Gatherv (:269-270)
    if (r == 0) {
      if (kk < 1 || kk > total) {
        ctl->error = 3;  // invariant violation — should be impossible
      } else {
        std::nth_element(arena, arena + (kk - 1), arena + total);  // :277-279
        ctl->answer = arena[kk - 1];
      }
    }
  } else if (r == 0) {
    ctl->answer = answer;
  }
  if (r == 0) {
    ctl->found = found ? 1 : 0;
    ctl->rounds = rounds;
    ctl->elapsed = now_s() - t0;
  }
  pthread_barrier_wait(&ctl->barrier);  // all ranks done before exit
}

}  // namespace

extern "C" {

int nth_element_i32(const int32_t* d, int64_t n, int64_t k, int32_t* o) {
  return nth_impl(d, n, k, o);
}
int nth_element_i64(const int64_t* d, int64_t n, int64_t k, int64_t* o) {
  return nth_impl(d, n, k, o);
}
int nth_element_f32(const float* d, int64_t n, int64_t k, float* o) {
  return nth_impl(d, n, k, o);
}
int nth_element_f64(const double* d, int64_t n, int64_t k, double* o) {
  return nth_impl(d, n, k, o);
}

// Distributed CGM k-selection over num_procs forked ranks.
// Returns 0 on success; 1 bad args (mirrors the world_size >= 2 abort at
// TODO-…:56-59), 2 runtime failure, 3 internal invariant violation.
int cgm_kselect_i32(const int32_t* data, int64_t n, int64_t k, int num_procs,
                    int64_t c, int32_t* answer, int64_t* rounds,
                    double* elapsed, int32_t* found_early) {
  if (!data || !answer || n <= 0 || k < 1 || k > n) return 1;
  if (num_procs < 2 || num_procs > kMaxProcs) return 1;  // MPI_Abort :56-59
  if (c < 1) return 1;

  const size_t arena_bytes = sizeof(Ctrl) + (size_t)n * sizeof(int32_t);
  void* shm = mmap(nullptr, arena_bytes, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_ANONYMOUS, -1, 0);
  if (shm == MAP_FAILED) return 2;
  Ctrl* ctl = new (shm) Ctrl();
  int32_t* arena = (int32_t*)((char*)shm + sizeof(Ctrl));
  std::memset(ctl, 0, sizeof(Ctrl));

  pthread_barrierattr_t attr;
  pthread_barrierattr_init(&attr);
  pthread_barrierattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  if (pthread_barrier_init(&ctl->barrier, &attr, num_procs) != 0) {
    munmap(shm, arena_bytes);
    return 2;
  }
  pthread_barrierattr_destroy(&attr);

  std::vector<pid_t> pids;
  int rc = 0;
  for (int r = 0; r < num_procs; r++) {
    pid_t pid = fork();
    if (pid < 0) {
      rc = 2;  // fork failed: kill and reap already-spawned ranks
      for (pid_t q : pids) kill(q, SIGKILL);
      for (pid_t q : pids) waitpid(q, nullptr, 0);
      break;
    }
    if (pid == 0) {
      cgm_rank(r, num_procs, data, n, k, c, ctl, arena);
      _exit(0);
    }
    pids.push_back(pid);
  }
  if (rc == 0) {
    // Reap with WNOHANG polling (never waitpid(-1): the hosting process may
    // own unrelated children). If any rank dies abnormally mid-protocol the
    // survivors are stuck in pthread_barrier_wait forever — kill the rest so
    // the call returns rc=2 instead of hanging in waitpid.
    std::vector<bool> done(pids.size(), false);
    size_t remaining = pids.size();
    bool killed = false;
    while (remaining > 0) {
      bool progressed = false;
      for (size_t i = 0; i < pids.size(); i++) {
        if (done[i]) continue;
        int status = 0;
        const pid_t w = waitpid(pids[i], &status, WNOHANG);
        if (w == 0) continue;
        done[i] = true;
        remaining--;
        progressed = true;
        if (w < 0 || !WIFEXITED(status) || WEXITSTATUS(status) != 0) rc = 2;
      }
      if (rc != 0 && !killed) {
        killed = true;
        for (size_t i = 0; i < pids.size(); i++)
          if (!done[i]) kill(pids[i], SIGKILL);
      }
      if (remaining > 0 && !progressed) usleep(1000);
    }
  }
  if (rc == 0 && ctl->error != 0) rc = ctl->error;
  if (rc == 0) {
    *answer = ctl->answer;
    if (rounds) *rounds = ctl->rounds;
    if (elapsed) *elapsed = ctl->elapsed;
    if (found_early) *found_early = ctl->found;
  }
  pthread_barrier_destroy(&ctl->barrier);
  munmap(shm, arena_bytes);
  return rc;
}

}  // extern "C"
