"""64-bit mode helper (int64/float64 selection needs jax x64 enabled)."""

from __future__ import annotations

import contextlib

from mpi_k_selection_tpu.utils import compat


def enable_x64():
    """Context manager enabling 64-bit types, across jax versions."""
    return compat.enable_x64(True)


@contextlib.contextmanager
def maybe_x64(active: bool):
    if active:
        with enable_x64():
            yield
    else:
        yield
