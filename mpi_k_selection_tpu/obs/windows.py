"""Windowed-histogram bridge: back any metrics-registry Histogram with a
sliding-window (optionally decayed) RadixSketch.

A fixed-bucket Prometheus histogram answers "p99 latency" by
interpolating inside whichever static bucket the rank lands in — the
error is the bucket width, chosen at registry time, forever. The repo
already owns a summary structure with EXACT rank/value bounds and an
O(1)-advance sliding window (monitor/windows.py), so its own telemetry
can do strictly better: a :class:`WindowedHistogram` keeps the full
Prometheus histogram contract (buckets/sum/count — nothing existing
changes) and ADDITIONALLY folds every observation into a
:class:`~mpi_k_selection_tpu.monitor.windows.WindowedSketch` over
``float64`` observation space, advancing every ``advance_every``
observations (observation counts, never clocks — KSL004).

Enable per metric name BEFORE the first observation::

    registry.enable_windowed("serve.latency_seconds", window=8,
                             advance_every=256)

Every labeled series of that name then carries windowed quantiles with
exact bounds — ``serve.latency_seconds{tier=...}`` p50/p90/p99 in
``/metrics`` become sliding-window order statistics instead of
fixed-bucket interpolation. Exposition stays Prometheus-conformant:
the extra series are GAUGES named ``<name>_windowed`` (value,
``quantile`` label), ``<name>_windowed_rank_error`` (the exact
worst-case rank error of that value) and ``<name>_windowed_count``
(observations live in the window), tested against the text-format
grammar in tests/test_prometheus.py. The serving layer surfaces this
as ``KSelectServer(latency_windows=...)`` — off by default; enabling it
never changes an answer bit (tests/test_serve.py).
"""

from __future__ import annotations

import math

from mpi_k_selection_tpu.obs.metrics import DEFAULT_BUCKETS, Histogram

#: Default quantile set of the windowed exposition series.
DEFAULT_WINDOW_QUANTILES = (0.5, 0.9, 0.99)


class WindowedHistogram(Histogram):
    """A registry Histogram whose observations ALSO feed a sliding
    window of RadixSketch buckets (``float64`` observation space).
    Created by the registry when :meth:`~mpi_k_selection_tpu.obs.
    metrics.MetricsRegistry.enable_windowed` named this metric; never
    constructed directly."""

    type_name = "histogram"

    def __init__(
        self, name, labels, lock, buckets=DEFAULT_BUCKETS, *,
        window: int = 8, advance_every: int = 256, radix_bits: int = 4,
        levels: int = 4, decay: float | None = None,
        quantiles=DEFAULT_WINDOW_QUANTILES,
    ):
        super().__init__(name, labels, lock, buckets=buckets)
        import numpy as np

        from mpi_k_selection_tpu.monitor.decay import DecayedWindowedSketch
        from mpi_k_selection_tpu.monitor.windows import WindowedSketch

        if decay is None:
            self.window_sketch = WindowedSketch(
                np.float64, window=window, radix_bits=radix_bits,
                levels=levels,
            )
        else:
            self.window_sketch = DecayedWindowedSketch(
                np.float64, window=window, decay=decay,
                radix_bits=radix_bits, levels=levels,
            )
        self.advance_every = int(advance_every)
        if self.advance_every < 1:
            raise ValueError(
                f"advance_every must be >= 1 observation, got {advance_every}"
            )
        self.window_quantiles = tuple(float(q) for q in quantiles)
        self._since_advance = 0

    def _observe_locked(self, value) -> None:
        super()._observe_locked(value)
        self.window_sketch.update_value(float(value))
        self._since_advance += 1
        if self._since_advance >= self.advance_every:
            self.window_sketch.advance()
            self._since_advance = 0

    def windowed_snapshot(self):
        """``[{q, value, rank_bounds, value_bounds, rank_error}, ...]``
        over the live window plus the window's count — ``None`` while
        the window is empty. The quantile values carry the merged
        sketch's EXACT bounds (weighted-rank space when decayed)."""
        with self._lock:
            m = self.window_sketch.query()
            if m.n == 0:
                return None
            out = []
            for q in self.window_quantiles:
                k = max(1, min(m.n, math.ceil(q * m.n)))
                lo, hi = m.rank_bounds(k)
                vlo, vhi = m.value_bounds(k)
                out.append(
                    {
                        "q": q,
                        "value": float(m.query(k)),
                        "rank_bounds": (int(lo), int(hi)),
                        "value_bounds": (float(vlo), float(vhi)),
                        "rank_error": int(hi - lo),
                    }
                )
            return {"n": int(m.n), "quantiles": out}

    def as_dict(self) -> dict:
        out = super().as_dict()
        snap = self.windowed_snapshot()
        out["windowed"] = None if snap is None else {
            "n": snap["n"],
            "window": self.window_sketch.window,
            "quantiles": {
                str(e["q"]): {
                    "value": e["value"],
                    "rank_bounds": list(e["rank_bounds"]),
                    "value_bounds": list(e["value_bounds"]),
                    "rank_error": e["rank_error"],
                }
                for e in snap["quantiles"]
            },
        }
        return out
