"""Per-device dispatch lanes — one supervised batcher per execution
device.

PR 7's batcher serialized ALL device work on one dispatch thread. That
is stronger than determinism needs: answers must be bit-identical to
serial execution PER DATASET (one dataset's coalesced walks must not
interleave), but two datasets resident on DIFFERENT devices share no
state at all — serializing them through one thread only makes one
chip's slow walk block another chip's fast one. This module keeps the
per-dataset guarantee and drops the accidental global one:

- **Lane key**: every resolved dataset maps to a deterministic lane key
  (:func:`lane_key_for`) — the sorted committed device set for device
  residency, ``"host"`` for the host-exact route, ``"stream"`` for
  out-of-core datasets (streamed descents manage their own staging
  devices; serializing them against each other preserves PR 7's
  behavior for the shared staging pool). A dataset's key never changes
  (resident shards are immutable), so all of its queries always land in
  the same lane and coalesce exactly as before.
- **Lanes are whole batchers**: each lane is a full
  :class:`~mpi_k_selection_tpu.serve.batcher.QueryBatcher` — coalescing
  window, deadline drops, admission control (``max_depth`` bounds EACH
  lane's queue), and supervised restarts all keep their PR 7 semantics
  inside the lane. A crash in one lane's loop restarts only that lane;
  the others never notice (tests/test_serve_lanes.py).
- **Lane count**: ``lanes="auto"`` (default) opens one lane per
  distinct key, lazily at first query. An integer ``lanes=N`` folds
  keys onto N lanes by CRC32 (a stable hash — ``hash()`` is
  process-seeded and KSL024 bars nondeterministic placement);
  ``lanes=1`` degenerates to exactly today's single batcher,
  bit-for-bit.

Threads are named ``ksel-serve-lane-<key>-dispatch-*`` — the
``ksel-serve`` family (resource_protocols.py), so the conftest
leaked-thread fixture and the KSL021 lifecycle pass cover lane threads
with no new vocabulary. ``close()`` closes every lane (joins every
dispatch thread) on all exit paths.
"""

from __future__ import annotations

import threading
import zlib

from mpi_k_selection_tpu.resource_protocols import SERVE_THREAD_PREFIX
from mpi_k_selection_tpu.serve.batcher import DEFAULT_MAX_BATCH, QueryBatcher
from mpi_k_selection_tpu.serve.errors import ServerClosedError


def lane_key_for(ds) -> str:
    """The dispatch-lane key of one resolved dataset: a pure function
    of the dataset's (immutable) residency, so every query against it
    lands in the same lane forever. Device residency keys by the sorted
    committed device set (a sharded array spanning devices gets one
    combined lane — its walk already fans across those chips)."""
    residency = getattr(ds, "residency", None)
    if residency == "device":
        try:
            devices = ds.data.devices()
        except AttributeError:
            return "device"
        return "+".join(sorted(str(d) for d in devices))
    if residency in ("host", "stream"):
        return residency
    return "default"


def validate_lanes(lanes):
    """``"auto"`` or an int >= 1."""
    if lanes == "auto":
        return lanes
    n = int(lanes)
    if n < 1:
        raise ValueError(f"lanes={lanes!r} must be 'auto' or an int >= 1")
    return n


class LaneDispatcher:
    """The server's dispatch surface: routes each
    :class:`~mpi_k_selection_tpu.serve.batcher.PendingQuery` to its
    dataset's lane, creating lanes lazily. Presents the same submit/
    restarts/closed/close surface as one ``QueryBatcher`` (the PR 7
    server's tests drive it unchanged); ``observe_depth`` and
    ``observe_restart`` gain a trailing ``lane`` name argument so the
    metrics can carry the per-lane label."""

    def __init__(
        self,
        execute_ranks,
        *,
        lanes="auto",
        window: float = 0.0,
        max_batch: int = DEFAULT_MAX_BATCH,
        max_depth: int | None = None,
        retry_after: float = 1.0,
        observe_depth=None,
        observe_width=None,
        observe_shed=None,
        observe_expired=None,
        observe_restart=None,
    ):
        self.lanes = validate_lanes(lanes)
        self._execute_ranks = execute_ranks
        self._window = window
        self._max_batch = max_batch
        self._max_depth = max_depth
        self._retry_after = retry_after
        self._observe_depth = observe_depth
        self._observe_width = observe_width
        self._observe_shed = observe_shed
        self._observe_expired = observe_expired
        self._observe_restart = observe_restart
        self._lock = threading.Lock()
        self._lanes: dict[str, QueryBatcher] = {}  # ksel: guarded-by[_lock]
        self._stop = False  # ksel: guarded-by[_lock]

    # -- routing -----------------------------------------------------------

    def _lane_name(self, ds) -> str:
        key = lane_key_for(ds)
        if self.lanes == "auto":
            return key
        if self.lanes == 1:
            # the single-lane degenerate case IS today's batcher: one
            # thread, one queue, every dataset serialized through it
            return "lane0"
        return f"lane{zlib.crc32(key.encode()) % self.lanes}"

    def _lane_for(self, ds) -> QueryBatcher:
        name = self._lane_name(ds)
        with self._lock:
            if self._stop:
                raise ServerClosedError("server is closed; query rejected")
            lane = self._lanes.get(name)
            if lane is None:
                lane = QueryBatcher(
                    self._execute_ranks,
                    window=self._window,
                    max_batch=self._max_batch,
                    max_depth=self._max_depth,
                    retry_after=self._retry_after,
                    observe_depth=self._wrap_depth(name),
                    observe_width=self._observe_width,
                    observe_shed=self._observe_shed,
                    observe_expired=self._observe_expired,
                    observe_restart=self._wrap_restart(name),
                    name=f"{SERVE_THREAD_PREFIX}-lane-{name}-dispatch",
                )
                self._lanes[name] = lane
        return lane

    def _wrap_depth(self, name: str):
        if self._observe_depth is None:
            return None
        return lambda depth: self._observe_depth(depth, name)

    def _wrap_restart(self, name: str):
        if self._observe_restart is None:
            return None
        return lambda exc: self._observe_restart(exc, name)

    # -- the QueryBatcher surface ------------------------------------------

    def submit(self, item):
        """Route to the item's dataset lane (created on first use) and
        enqueue — admission control and closed checks are the lane's."""
        return self._lane_for(item.ds).submit(item)

    @property
    def restarts(self) -> int:
        """Supervisor restarts summed over every lane (the
        ``serve.dispatch_restarts`` figure)."""
        with self._lock:
            lanes = list(self._lanes.values())
        return sum(lane.restarts for lane in lanes)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._stop

    @property
    def depth(self) -> int:
        """Queued queries summed over every lane (approximate)."""
        with self._lock:
            lanes = list(self._lanes.values())
        return sum(lane.depth for lane in lanes)

    @property
    def lane_count(self) -> int:
        with self._lock:
            return len(self._lanes)

    def lane_summary(self) -> dict:
        """Per-lane occupancy snapshot: ``{lane: {submitted,
        queue_depth, restarts}}`` — the /debug/bundle "lanes" section
        and the tpu_smoke occupancy print."""
        with self._lock:
            lanes = dict(self._lanes)
        return {
            name: {
                "submitted": int(lane.submitted),
                "queue_depth": int(lane.depth),
                "restarts": int(lane.restarts),
            }
            for name, lane in sorted(lanes.items())
        }

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Stop admitting (new lanes AND new submits), then drain and
        join every lane's dispatch thread. Idempotent; a submit racing
        close either fails here-or-there with
        :class:`~mpi_k_selection_tpu.serve.errors.ServerClosedError` or
        is drained by its lane's own close."""
        with self._lock:
            self._stop = True
            lanes = list(self._lanes.values())
        for lane in lanes:
            lane.close()
