"""64-bit mode helper (int64/float64 selection needs jax x64 enabled)."""

from __future__ import annotations

import contextlib

import jax


def enable_x64():
    """Context manager enabling 64-bit types, across jax versions."""
    if hasattr(jax, "enable_x64"):  # jax >= 0.9
        return jax.enable_x64(True)
    from jax.experimental import enable_x64 as _legacy  # pragma: no cover

    return _legacy()  # pragma: no cover


@contextlib.contextmanager
def maybe_x64(active: bool):
    if active:
        with enable_x64():
            yield
    else:
        yield
